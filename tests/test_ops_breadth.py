"""Breadth tests for the expanded op registry — the reference's
declarable-op families (reduce3 distances, summary stats, index
reductions, scatter, random, sequence, image, special math)."""

import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.ops_registry import OPS, get_op


def _np(x):
    return np.asarray(x)


def test_reduce3_distances():
    a = np.array([1.0, 0.0, 0.0], np.float32)
    b = np.array([0.0, 1.0, 0.0], np.float32)
    assert _np(OPS["cosine_similarity"](a, b)) == pytest.approx(0.0, abs=1e-6)
    assert _np(OPS["cosine_distance"](a, b)) == pytest.approx(1.0, abs=1e-6)
    assert _np(OPS["euclidean_distance"](a, b)) == pytest.approx(np.sqrt(2), abs=1e-6)
    assert _np(OPS["manhattan_distance"](a, b)) == pytest.approx(2.0)
    assert _np(OPS["hamming_distance"](a, b)) == pytest.approx(2.0)
    assert _np(OPS["dot"](a, a)) == pytest.approx(1.0)
    # jaccard on non-negative vectors: 1 - min/max
    assert _np(OPS["jaccard_distance"](a, a)) == pytest.approx(0.0, abs=1e-6)


def test_reduction_breadth():
    x = np.array([[-1.0, 0.0, 2.0], [3.0, -4.0, 0.0]], np.float32)
    assert _np(OPS["norm1"](x)) == pytest.approx(10.0)
    assert _np(OPS["norm_max"](x)) == pytest.approx(4.0)
    assert _np(OPS["squared_norm"](x)) == pytest.approx(1 + 4 + 9 + 16)
    assert _np(OPS["count_nonzero"](x)) == pytest.approx(4.0)
    assert _np(OPS["count_zero"](x)) == pytest.approx(2.0)
    assert _np(OPS["amax"](x)) == pytest.approx(4.0)
    assert _np(OPS["amin"](x)) == pytest.approx(0.0)
    m = _np(OPS["moments"](x))
    assert m[0] == pytest.approx(x.mean())
    assert m[1] == pytest.approx(x.var())
    p = np.array([0.5, 0.5], np.float32)
    assert _np(OPS["entropy"](p)) == pytest.approx(np.log(2), abs=1e-6)
    assert _np(OPS["shannon_entropy"](p)) == pytest.approx(1.0, abs=1e-6)
    assert _np(OPS["median"](np.array([1.0, 3.0, 2.0]))) == pytest.approx(2.0)
    assert _np(OPS["percentile"](np.arange(101.0), q=50)) == pytest.approx(50.0)


def test_index_reductions():
    x = np.array([1.0, -5.0, 3.0, 0.0], np.float32)
    assert int(_np(OPS["iamax"](x))) == 1
    assert int(_np(OPS["iamin"](x))) == 3
    y = np.array([0.0, 0.0, 7.0, 0.0, 2.0], np.float32)
    assert int(_np(OPS["first_index_nonzero"](y))) == 2
    assert int(_np(OPS["last_index_nonzero"](y))) == 4
    z = np.zeros(5, np.float32)
    assert int(_np(OPS["first_index_nonzero"](z))) == -1
    assert int(_np(OPS["last_index_nonzero"](z))) == -1


def test_scatter_family():
    ref = np.zeros((4, 2), np.float32)
    idx = np.array([1, 3, 1])
    upd = np.ones((3, 2), np.float32)
    out = _np(OPS["scatter_add"](ref, idx, upd))
    assert out[1].tolist() == [2.0, 2.0] and out[3].tolist() == [1.0, 1.0]
    out = _np(OPS["scatter_update"](ref + 5.0, idx, upd))
    assert out[1].tolist() == [1.0, 1.0] and out[0].tolist() == [5.0, 5.0]
    out = _np(OPS["scatter_max"](ref + 0.5, np.array([0]), np.array([[9.0, 0.0]])))
    assert out[0].tolist() == [9.0, 0.5]


def test_gather_scatter_nd():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    idx = np.array([[0, 1], [2, 3]])
    assert _np(OPS["gather_nd"](x, idx)).tolist() == [1.0, 11.0]
    out = _np(OPS["scatter_nd"](idx, np.array([5.0, 7.0], np.float32), shape=(3, 4)))
    assert out[0, 1] == 5.0 and out[2, 3] == 7.0 and out.sum() == 12.0


def test_random_family_deterministic():
    a = _np(OPS["random_normal"](shape=(64,), seed=3, mean=1.0, std=2.0))
    b = _np(OPS["random_normal"](shape=(64,), seed=3, mean=1.0, std=2.0))
    np.testing.assert_array_equal(a, b)
    u = _np(OPS["random_uniform"](shape=(256,), seed=1, minval=2.0, maxval=3.0))
    assert u.min() >= 2.0 and u.max() <= 3.0
    bern = _np(OPS["random_bernoulli"](shape=(1000,), seed=0, p=0.25))
    assert 0.15 < bern.mean() < 0.35


def test_creation_and_sequence_ops():
    assert _np(OPS["eye"](n=3)).trace() == 3.0
    assert _np(OPS["linspace"](start=0.0, stop=1.0, num=5)).tolist() == [
        0.0, 0.25, 0.5, 0.75, 1.0]
    assert _np(OPS["range"](start=0, limit=6, delta=2)).tolist() == [0.0, 2.0, 4.0]
    assert _np(OPS["fill"](shape=(2, 2), value=7.0)).sum() == 28.0
    mask = _np(OPS["sequence_mask"](np.array([1, 3]), maxlen=4))
    assert mask.tolist() == [[1, 0, 0, 0], [1, 1, 1, 0]]
    x = np.arange(8, dtype=np.float32).reshape(2, 4)
    rev = _np(OPS["reverse_sequence"](x, np.array([2, 4])))
    assert rev[0].tolist() == [1.0, 0.0, 2.0, 3.0]
    assert rev[1].tolist() == [7.0, 6.0, 5.0, 4.0]


def test_matrix_structure_ops():
    x = np.arange(9, dtype=np.float32).reshape(3, 3)
    band = _np(OPS["matrix_band_part"](x, lower=0, upper=0))
    assert band.sum() == x.trace()
    d = _np(OPS["matrix_diag"](np.array([1.0, 2.0])))
    assert d.tolist() == [[1.0, 0.0], [0.0, 2.0]]
    s = _np(OPS["matrix_set_diag"](np.zeros((2, 2), np.float32), np.array([3.0, 4.0])))
    assert s[0, 0] == 3.0 and s[1, 1] == 4.0


def test_hsv_round_trip_and_adjust():
    rng = np.random.default_rng(0)
    img = rng.uniform(0, 1, (2, 4, 4, 3)).astype(np.float32)
    back = _np(OPS["hsv_to_rgb"](OPS["rgb_to_hsv"](img)))
    np.testing.assert_allclose(back, img, atol=1e-5)
    sat = _np(OPS["adjust_saturation"](img, factor=0.0))
    # zero saturation -> grayscale: channels equal
    np.testing.assert_allclose(sat[..., 0], sat[..., 1], atol=1e-5)
    hue = _np(OPS["adjust_hue"](img, delta=1.0))   # full rotation = identity
    np.testing.assert_allclose(hue, img, atol=1e-4)


def test_crop_and_resize():
    img = np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1)
    boxes = np.array([[0.0, 0.0, 1.0, 1.0]], np.float32)     # whole image
    out = _np(OPS["crop_and_resize"](img, boxes, np.array([0]), crop_size=(4, 4)))
    np.testing.assert_allclose(out, img, atol=1e-5)
    half = np.array([[0.0, 0.0, 0.0, 1.0]], np.float32)      # top row only
    out = _np(OPS["crop_and_resize"](img, half, np.array([0]), crop_size=(1, 4)))
    np.testing.assert_allclose(out[0, 0, :, 0], [0, 1, 2, 3], atol=1e-5)


def test_non_max_suppression():
    boxes = np.array(
        [[0, 0, 1, 1], [0, 0, 1.05, 1.05], [2, 2, 3, 3]], np.float32
    )
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    sel = _np(OPS["non_max_suppression"](boxes, scores, max_output_size=3,
                                         iou_threshold=0.5))
    assert sel.tolist() == [0, 2, -1]


def test_space_batch_round_trip():
    x = np.random.default_rng(1).normal(size=(2, 4, 4, 3)).astype(np.float32)
    s = OPS["space_to_batch"](x, block=2)
    assert s.shape == (8, 2, 2, 3)
    back = _np(OPS["batch_to_space"](s, block=2))
    np.testing.assert_allclose(back, x, atol=1e-6)


def test_confusion_matrix_and_misc():
    cm = _np(OPS["confusion_matrix"](np.array([0, 1, 1]), np.array([0, 0, 1]),
                                     num_classes=2))
    assert cm.tolist() == [[1.0, 0.0], [1.0, 1.0]]
    x = np.array([-2.0, 0.5, 3.0], np.float32)
    assert _np(OPS["thresholded_relu"](x, theta=1.0)).tolist() == [0.0, 0.0, 3.0]
    alpha = np.array([0.1], np.float32)
    np.testing.assert_allclose(
        _np(OPS["prelu"](x, alpha)), [-0.2, 0.5, 3.0], atol=1e-6
    )
    clipped = _np(OPS["clip_by_norm"](np.array([3.0, 4.0]), clip_norm=1.0))
    assert np.linalg.norm(clipped) == pytest.approx(1.0, abs=1e-5)
    st = _np(OPS["standardize"](np.array([[1.0, 2.0, 3.0]], np.float32)))
    assert st.mean() == pytest.approx(0.0, abs=1e-5)


def test_special_math():
    import scipy.special as sp

    x = np.array([0.5, 1.5, 3.0])
    np.testing.assert_allclose(_np(OPS["lgamma"](x)), sp.gammaln(x), atol=1e-5)
    np.testing.assert_allclose(_np(OPS["digamma"](x)), sp.psi(x), atol=1e-5)
    np.testing.assert_allclose(
        _np(OPS["igamma"](np.array(2.0), x)), sp.gammainc(2.0, x), atol=1e-5
    )
    assert _np(OPS["truncate_div"](np.array(7.0), np.array(2.0))) == 3.0


def test_samediff_namespace_exposure():
    from deeplearning4j_tpu.autodiff import SameDiff

    sd = SameDiff()
    a = sd.var("a", np.array([3.0, 4.0], np.float32))
    b = sd.var("b", np.array([1.0, 0.0], np.float32))
    d = sd.math.euclidean_distance(a, b)
    assert float(d.eval()) == pytest.approx(np.sqrt(4 + 16))
    r = sd.random.random_normal(shape=(4,), seed=1)
    assert r.eval().shape == (4,)
    m = sd.linalg.matrix_diag(a)
    assert m.eval().shape == (2, 2)


def test_get_op_unknown_raises():
    with pytest.raises(KeyError):
        get_op("definitely_not_an_op")


class TestNewOpGradients:
    """Finite-difference gradient checks for the differentiable additions
    (the OpValidation harness applied to the breadth ops)."""

    @pytest.mark.parametrize("name,args,attrs", [
        ("prelu", (np.array([-2.0, 0.5, 3.0], np.float32),
                   np.array([0.2], np.float32)), {}),
        ("mish", (np.array([-1.0, 0.3, 2.0], np.float32),), {}),
        ("log_sigmoid", (np.array([-1.0, 0.3, 2.0], np.float32),), {}),
        ("thresholded_relu", (np.array([-1.0, 0.5, 2.0], np.float32),),
         {"theta": 0.4}),
        ("standardize", (np.array([[1.0, 2.0, 4.0]], np.float32),), {}),
        ("clip_by_norm", (np.array([3.0, 4.0], np.float32),),
         {"clip_norm": 1.0}),
        ("cosine_similarity", (np.array([1.0, 2.0, 0.5], np.float32),
                               np.array([0.3, -1.0, 2.0], np.float32)), {}),
        ("euclidean_distance", (np.array([1.0, 2.0], np.float32),
                                np.array([0.0, -1.0], np.float32)), {}),
        ("lrn", (np.random.default_rng(0).normal(
            0, 1, (2, 3, 3, 8)).astype(np.float32),), {"size": 3}),
        ("matrix_set_diag", (np.ones((3, 3), np.float32),
                             np.array([1.0, 2.0, 3.0], np.float32)), {}),
    ])
    def test_gradient_matches_finite_difference(self, name, args, attrs):
        import jax
        import jax.numpy as jnp

        fn = OPS[name]

        def loss(*xs):
            return jnp.sum(fn(*xs, **attrs) ** 2)

        grads = jax.grad(loss, argnums=tuple(range(len(args))))(*args)
        eps = 1e-3
        for ai, (a, g) in enumerate(zip(args, grads)):
            flat = a.reshape(-1)
            gflat = np.asarray(g).reshape(-1)
            for i in range(min(flat.size, 6)):
                bump = np.zeros_like(flat)
                bump[i] = eps
                args_p = list(args)
                args_m = list(args)
                args_p[ai] = (flat + bump).reshape(a.shape)
                args_m[ai] = (flat - bump).reshape(a.shape)
                fd = (float(loss(*args_p)) - float(loss(*args_m))) / (2 * eps)
                assert abs(fd - gflat[i]) < 2e-2 * max(1.0, abs(fd)), (
                    name, ai, i, fd, gflat[i],
                )


class TestSignalFamily:
    """Audio/signal declarable ops (the reference's audio op family)."""

    def test_windows(self):
        for name in ("hann_window", "hamming_window", "blackman_window"):
            w = _np(OPS[name](length=16))
            # blackman dips infinitesimally below zero at the edges
            assert w.shape == (16,) and w.min() >= -1e-6 and w.max() <= 1.0

    def test_frame(self):
        x = np.arange(10, dtype=np.float32)
        f = _np(OPS["frame"](x, frame_length=4, frame_step=2))
        assert f.shape == (4, 4)
        np.testing.assert_array_equal(f[0], [0, 1, 2, 3])
        np.testing.assert_array_equal(f[1], [2, 3, 4, 5])

    def test_fft_roundtrip(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(3, 16)).astype(np.float32)
        X = OPS["fft"](x)
        back = _np(OPS["real"](OPS["ifft"](X)))
        np.testing.assert_allclose(back, x, atol=1e-5)
        Xr = OPS["rfft"](x)
        assert Xr.shape == (3, 9)
        np.testing.assert_allclose(_np(OPS["irfft"](Xr)), x, atol=1e-5)
        assert _np(OPS["complex_abs"](Xr)).dtype != np.complex64
        _ = OPS["angle"](Xr), OPS["imag"](Xr)

    def test_stft_istft_reconstructs(self):
        rng = np.random.default_rng(1)
        x = rng.normal(size=(2, 256)).astype(np.float32)
        S = OPS["stft"](x, frame_length=64, frame_step=16)
        assert S.shape == (2, 13, 33)
        y = _np(OPS["istft"](S, frame_length=64, frame_step=16))
        # interior reconstructs (edges lack full overlap coverage)
        np.testing.assert_allclose(y[:, 64:192], x[:, 64:192], atol=1e-4)


class TestReductionTail:
    def test_all_any(self):
        x = np.array([[1.0, 0.0], [1.0, 1.0]], np.float32)
        np.testing.assert_array_equal(_np(OPS["all"](x, axis=1)), [0.0, 1.0])
        np.testing.assert_array_equal(_np(OPS["any"](x, axis=1)), [1.0, 1.0])

    def test_unsorted_segments(self):
        x = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
        ids = np.array([0, 1, 0, 1], np.int32)
        np.testing.assert_allclose(
            _np(OPS["unsorted_segment_sum"](x, ids, num_segments=2)), [4.0, 6.0]
        )
        np.testing.assert_allclose(
            _np(OPS["unsorted_segment_mean"](x, ids, num_segments=2)), [2.0, 3.0]
        )
        np.testing.assert_allclose(
            _np(OPS["unsorted_segment_max"](x, ids, num_segments=2)), [3.0, 4.0]
        )
        np.testing.assert_allclose(
            _np(OPS["unsorted_segment_prod"](x, ids, num_segments=2)), [3.0, 8.0]
        )

    def test_cumulative_logsumexp(self):
        x = np.array([0.0, 0.0, 0.0], np.float32)
        out = _np(OPS["cumulative_logsumexp"](x))
        np.testing.assert_allclose(out, np.log([1.0, 2.0, 3.0]), atol=1e-5)

    def test_bucketing_ops(self):
        x = np.array([3, 1, 3, 2], np.float32)
        u = _np(OPS["unique_with_pad"](x, size=4, fill=0))
        assert set(u.tolist()) == {0.0, 1.0, 2.0, 3.0}
        np.testing.assert_array_equal(
            _np(OPS["bincount"](x, length=5)), [0, 1, 1, 2, 0]
        )
        h = _np(OPS["histogram_fixed_width"](x, lo=0.0, hi=4.0, nbins=4))
        assert h.sum() == 4
        perm = np.array([2, 0, 1], np.int32)
        np.testing.assert_array_equal(
            _np(OPS["invert_permutation"](perm)), [1, 2, 0]
        )
        np.testing.assert_array_equal(
            _np(OPS["searchsorted"](np.array([1.0, 3.0, 5.0]), x)), [1, 0, 1, 1]
        )
        y = _np(OPS["nan_to_num"](np.array([np.nan, np.inf, 1.0], np.float32)))
        assert np.isfinite(y).all()


class TestLinalgTail:
    def test_eigh_and_logdet(self):
        rng = np.random.default_rng(0)
        a = rng.normal(size=(4, 4)).astype(np.float32)
        spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        w = _np(OPS["eigh_values"](spd))
        assert (w > 0).all() and np.all(np.diff(w) >= -1e-4)
        v = _np(OPS["eigh_vectors"](spd))
        np.testing.assert_allclose(v @ np.diag(w) @ v.T, spd, atol=1e-3)
        np.testing.assert_allclose(
            float(OPS["logdet"](spd)), np.linalg.slogdet(spd)[1], atol=1e-4
        )
        assert float(OPS["slogdet_sign"](spd)) == 1.0

    def test_solve_power_kron_pinv(self):
        rng = np.random.default_rng(1)
        L = np.tril(rng.normal(size=(3, 3))).astype(np.float32) + 3 * np.eye(3, dtype=np.float32)
        b = rng.normal(size=(3, 2)).astype(np.float32)
        x = _np(OPS["triangular_solve"](L, b, lower=True))
        np.testing.assert_allclose(L @ x, b, atol=1e-4)
        m = np.array([[1.0, 1.0], [0.0, 1.0]], np.float32)
        np.testing.assert_allclose(
            _np(OPS["matrix_power"](m, n=3)), [[1, 3], [0, 1]], atol=1e-5
        )
        k = _np(OPS["kron"](np.eye(2, dtype=np.float32), m))
        assert k.shape == (4, 4)
        p = _np(OPS["pinv"](m))
        np.testing.assert_allclose(p @ m, np.eye(2), atol=1e-4)
        assert float(OPS["matrix_rank"](m)) == 2.0
        e = _np(OPS["expm"](np.zeros((2, 2), np.float32)))
        np.testing.assert_allclose(e, np.eye(2), atol=1e-6)


class TestLossTail:
    def test_losses_sane(self):
        rng = np.random.default_rng(0)
        pred = rng.uniform(0.1, 0.9, (8, 4)).astype(np.float32)
        target = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 8)]
        assert float(OPS["huber_loss"](pred, target, delta=1.0)) >= 0
        assert float(OPS["absolute_difference"](pred, target)) >= 0
        assert float(OPS["log_loss"](pred, target)) >= 0
        assert float(OPS["poisson_loss"](pred, target)) > -np.inf
        p = np.full((8, 4), 0.25, np.float32)
        assert abs(float(OPS["kl_divergence"](p, p))) < 1e-6
        assert float(OPS["kl_divergence"](target + 1e-6, p)) > 0.1
        assert float(OPS["hinge_loss"](pred, 2 * target - 1)) >= 0
        same = float(OPS["cosine_proximity_loss"](target, target))
        assert abs(same + 1.0) < 1e-5

    def test_huber_gradient(self):
        import jax

        g = jax.grad(lambda p, t: OPS["huber_loss"](p, t, delta=1.0))(
            np.array([0.5, 5.0], np.float32), np.array([0.0, 0.0], np.float32)
        )
        np.testing.assert_allclose(_np(g), [0.25, 0.5], atol=1e-5)


class TestRandomAndActivationTail:
    def test_random_tail_deterministic(self):
        for name, kw in [
            ("random_gamma", {"alpha": 2.0}),
            ("random_poisson", {"lam": 3.0}),
            ("random_truncated_normal", {}),
        ]:
            a = _np(OPS[name](shape=(64,), seed=7, **kw))
            b = _np(OPS[name](shape=(64,), seed=7, **kw))
            np.testing.assert_array_equal(a, b)
            assert a.shape == (64,)
        x = np.arange(10, dtype=np.float32)
        s = _np(OPS["random_shuffle"](x, seed=3))
        assert sorted(s.tolist()) == x.tolist() and not np.array_equal(s, x)
        tn = _np(OPS["random_truncated_normal"](shape=(256,), seed=1))
        assert np.abs(tn).max() <= 2.0 + 1e-6

    def test_activation_tail(self):
        x = np.linspace(-3, 3, 13).astype(np.float32)
        hs = _np(OPS["hard_swish"](x))
        assert hs[0] == 0.0 and abs(hs[-1] - 3.0) < 1e-6
        c = _np(OPS["celu"](x, alpha=1.0))
        assert (c >= -1.0 - 1e-6).all()
        g = _np(OPS["glu"](np.ones((2, 4), np.float32)))
        assert g.shape == (2, 2)


def test_unsorted_segment_empty_segment_fills():
    """TF semantics on EMPTY segments: mean fills 0 (not NaN), max/min
    fill the dtype's finite lowest/highest (not +/-inf)."""
    x = np.array([1.0, 3.0], np.float32)
    ids = np.array([0, 0], np.int32)
    mean = _np(OPS["unsorted_segment_mean"](x, ids, num_segments=3))
    np.testing.assert_allclose(mean, [2.0, 0.0, 0.0])
    mx = _np(OPS["unsorted_segment_max"](x, ids, num_segments=3))
    assert mx[0] == 3.0 and np.isfinite(mx).all()
    mn = _np(OPS["unsorted_segment_min"](x, ids, num_segments=3))
    assert mn[0] == 1.0 and np.isfinite(mn).all()


class TestRegistryTail2:
    def test_elementwise_tail(self):
        x = np.array([-1.5, 0.0, 2.5], np.float32)
        np.testing.assert_allclose(_np(OPS["rint"](x)), np.rint(x))
        np.testing.assert_allclose(
            _np(OPS["heaviside"](x, value=0.5)), [0.0, 0.5, 1.0]
        )
        np.testing.assert_allclose(
            _np(OPS["copysign"](np.abs(x), x)), x
        )
        np.testing.assert_allclose(
            _np(OPS["hypot"](np.array([3.0]), np.array([4.0]))), [5.0]
        )
        np.testing.assert_allclose(
            _np(OPS["logaddexp"](np.zeros(1), np.zeros(1))), [np.log(2)],
            atol=1e-6,
        )
        np.testing.assert_allclose(_np(OPS["deg2rad"](np.array([180.0]))),
                                   [np.pi], atol=1e-6)
        np.testing.assert_allclose(
            _np(OPS["lerp"](np.zeros(3), np.ones(3), weight=0.25)),
            [0.25] * 3,
        )
        p = np.array([0.5], np.float32)
        np.testing.assert_allclose(_np(OPS["logit"](p)), [0.0], atol=1e-6)
        np.testing.assert_allclose(
            _np(OPS["erfinv"](np.array([0.0]))), [0.0], atol=1e-6
        )
        np.testing.assert_allclose(
            _np(OPS["ndtr"](np.array([0.0]))), [0.5], atol=1e-6
        )
        assert _np(OPS["popcount"](np.array([7]))).tolist() == [3]
        assert _np(OPS["isclose"](np.ones(2), np.ones(2))).tolist() == [1.0, 1.0]

    def test_nan_reductions_and_cummax(self):
        x = np.array([1.0, np.nan, 3.0], np.float32)
        assert float(OPS["nansum"](x)) == 4.0
        assert float(OPS["nanmean"](x)) == 2.0
        assert float(OPS["nanmax"](x)) == 3.0
        assert float(OPS["nanmin"](x)) == 1.0
        assert np.isfinite(float(OPS["nanstd"](x)))
        assert float(OPS["ptp"](np.array([2.0, 7.0, 3.0]))) == 5.0
        np.testing.assert_allclose(
            _np(OPS["cummax"](np.array([1.0, 3.0, 2.0]))), [1.0, 3.0, 3.0]
        )
        np.testing.assert_allclose(
            _np(OPS["cummin"](np.array([3.0, 1.0, 2.0]))), [3.0, 1.0, 1.0]
        )

    def test_linalg_tail2(self):
        a = np.array([1.0, 2.0], np.float32)
        assert _np(OPS["outer"](a, a)).shape == (2, 2)
        c = _np(OPS["cross"](np.array([1.0, 0, 0]), np.array([0, 1.0, 0])))
        np.testing.assert_allclose(c, [0, 0, 1.0])
        v = _np(OPS["vander"](a, n=3))
        assert v.shape == (2, 3)
        d = _np(OPS["diagflat"](a))
        assert d[0, 0] == 1.0 and d[1, 1] == 2.0
        m = np.array([[3.0, 0.0], [0.0, 4.0]], np.float32)
        assert float(OPS["matrix_norm"](m)) == 5.0
        assert float(OPS["cond_number"](np.eye(3, dtype=np.float32))) == 1.0
        lu = _np(OPS["lu_factor"](m + 1.0))
        assert lu.shape == (2, 2)

    def test_image_tail(self):
        rng = np.random.default_rng(0)
        img = rng.uniform(0, 1, (2, 8, 8, 3)).astype(np.float32)
        g = _np(OPS["image_gradients"](img))
        assert g.shape == (2, 2, 8, 8, 3)
        # dy of a vertical ramp is constant 1
        ramp = np.tile(np.arange(8.0)[None, :, None, None], (1, 1, 8, 1)).astype(np.float32)
        gr = _np(OPS["image_gradients"](ramp))
        np.testing.assert_allclose(gr[0][0, :-1], 1.0, atol=1e-6)
        s = _np(OPS["sobel_edges"](img))
        assert s.shape == (2, 2, 8, 8, 3)
        tv = _np(OPS["total_variation"](np.zeros((1, 4, 4, 1), np.float32)))
        assert tv.shape == (1,) and tv[0] == 0.0
        assert float(_np(OPS["psnr"](img, img)).min()) > 100.0
        np.testing.assert_allclose(_np(OPS["ssim"](img, img)), 1.0, atol=1e-4)
        assert _np(OPS["rot90"](img)).shape == (2, 8, 8, 3)
        gray = img[..., :1]
        assert _np(OPS["grayscale_to_rgb"](gray)).shape == (2, 8, 8, 3)
        cc = _np(OPS["central_crop"](img, fraction=0.5))
        assert cc.shape == (2, 4, 4, 3)

    def test_fake_quant_straight_through(self):
        import jax

        x = np.linspace(-8, 8, 9).astype(np.float32)
        q = _np(OPS["fake_quant"](x, min_val=-6.0, max_val=6.0, num_bits=8))
        assert q.min() >= -6.0 and q.max() <= 6.0
        # straight-through gradient: 1 inside range, 0 outside
        g = jax.grad(lambda v: OPS["fake_quant"](v, min_val=-6.0, max_val=6.0).sum())(x)
        g = _np(g)
        assert g[0] == 0.0 and g[4] == 1.0 and g[-1] == 0.0

    def test_loss_tail2_and_random_tail2(self):
        logits = np.array([[0.5, -0.5]], np.float32)
        labels = np.array([[1.0, 0.0]], np.float32)
        w = float(OPS["weighted_cross_entropy_with_logits"](
            logits, labels, pos_weight=2.0))
        assert w > 0
        assert float(OPS["log_cosh_loss"](logits, labels)) > 0
        for name, kw in [
            ("random_laplace", {}), ("random_cauchy", {}),
            ("random_rademacher", {}), ("random_beta", {"a": 2.0, "b": 3.0}),
        ]:
            a = _np(OPS[name](shape=(32,), seed=5, **kw))
            b = _np(OPS[name](shape=(32,), seed=5, **kw))
            np.testing.assert_array_equal(a, b)
        cat = _np(OPS["random_categorical"](
            np.zeros((2, 5), np.float32), num_samples=7, seed=1))
        assert cat.shape == (2, 7) and cat.max() < 5

    def test_activation_tail2(self):
        x = np.array([-2.0, -0.2, 0.2, 2.0], np.float32)
        ss = _np(OPS["softshrink"](x, lambd=0.5))
        np.testing.assert_allclose(ss, [-1.5, 0.0, 0.0, 1.5])
        hs = _np(OPS["hardshrink"](x, lambd=0.5))
        np.testing.assert_allclose(hs, [-2.0, 0.0, 0.0, 2.0])
        ts = _np(OPS["tanhshrink"](x))
        np.testing.assert_allclose(ts, x - np.tanh(x), atol=1e-6)


# --- round-4 op tail --------------------------------------------------------


class TestCtcFamily:
    def _brute_force_ctc(self, logp, labels, blank=0):
        """Exact -log P(labels) by enumerating ALL alignment paths."""
        import itertools

        T, C = logp.shape
        total = 0.0
        for path in itertools.product(range(C), repeat=T):
            # collapse path -> label
            out = []
            prev = -1
            for s in path:
                if s != prev and s != blank:
                    out.append(s)
                prev = s
            if out == list(labels):
                total += np.exp(sum(logp[t, s] for t, s in enumerate(path)))
        return -np.log(total)

    def test_ctc_loss_matches_brute_force(self):
        rng = np.random.default_rng(0)
        T, C = 5, 3
        logits = rng.normal(size=(1, T, C)).astype(np.float32)
        labels = np.array([[1, 2]], np.int32)
        got = float(_np(OPS["ctc_loss"](logits, labels)))
        logp = np.asarray(logits[0]) - np.log(
            np.exp(logits[0]).sum(-1, keepdims=True))
        want = self._brute_force_ctc(logp, [1, 2])
        assert got == pytest.approx(want, abs=1e-4)

    def test_ctc_loss_repeated_label_needs_blank(self):
        # labels [1,1]: paths must insert a blank between the 1s
        rng = np.random.default_rng(1)
        logits = rng.normal(size=(1, 4, 2)).astype(np.float32)
        labels = np.array([[1, 1]], np.int32)
        got = float(_np(OPS["ctc_loss"](logits, labels, blank=0)))
        logp = np.asarray(logits[0]) - np.log(
            np.exp(logits[0]).sum(-1, keepdims=True))
        want = self._brute_force_ctc(logp, [1, 1])
        assert got == pytest.approx(want, abs=1e-4)

    def test_ctc_loss_finite_difference_grad(self):
        import jax

        rng = np.random.default_rng(2)
        logits = rng.normal(size=(2, 6, 4)).astype(np.float64)
        labels = np.array([[1, 2, 3], [2, 2, 1]], np.int32)

        f = lambda lg: OPS["ctc_loss"](lg, labels)
        g = np.asarray(jax.grad(lambda lg: f(lg))(logits.astype(np.float32)))
        eps = 1e-3
        for idx in [(0, 0, 1), (1, 3, 2), (0, 5, 0)]:
            lp = logits.copy()
            lp[idx] += eps
            lm = logits.copy()
            lm[idx] -= eps
            fd = (float(_np(f(lp.astype(np.float32))))
                  - float(_np(f(lm.astype(np.float32))))) / (2 * eps)
            assert g[idx] == pytest.approx(fd, abs=5e-3), idx

    def test_ctc_loss_respects_lengths(self):
        rng = np.random.default_rng(3)
        logits = rng.normal(size=(1, 6, 3)).astype(np.float32)
        labels = np.array([[1, 2, 0]], np.int32)   # padded to S=3
        short = float(_np(OPS["ctc_loss"](
            logits, labels,
            logit_lengths=np.array([4]), label_lengths=np.array([2]))))
        # identical to trimming by hand
        trimmed = float(_np(OPS["ctc_loss"](
            logits[:, :4], labels[:, :2])))
        assert short == pytest.approx(trimmed, abs=1e-5)

    def test_ctc_loss_empty_labels_all_blank_path(self):
        # S=0: loss is -log P(all-blank); uniform logits -> T*log(C)
        z = float(_np(OPS["ctc_loss"](
            np.zeros((2, 4, 3), np.float32), np.zeros((2, 0), np.int32))))
        assert z == pytest.approx(4 * np.log(3.0), abs=1e-4)

    def test_in_top_k_tie_semantics(self):
        # TF: only strictly-greater entries spend the top-k budget
        p = np.array([[1.0, 1.0, 1.0]], np.float32)
        assert bool(_np(OPS["in_top_k"](p, np.array([0]), k=1))[0])

    def test_ctc_greedy_decode(self):
        # frames argmax to [1,1,0,2,2] -> collapse -> [1,2]
        logits = np.full((1, 5, 3), -5.0, np.float32)
        for t, c in enumerate([1, 1, 0, 2, 2]):
            logits[0, t, c] = 5.0
        out = _np(OPS["ctc_greedy_decode"](logits))
        n = _np(OPS["ctc_greedy_decode_lengths"](logits))
        assert n[0] == 2
        assert list(out[0][:2]) == [1, 2]
        assert all(v == -1 for v in out[0][2:])


class TestMorphologyAndArgmaxPool:
    def test_dilation_erosion_manual(self):
        x = np.zeros((1, 3, 3, 1), np.float32)
        x[0, 1, 1, 0] = 1.0
        filt = np.zeros((3, 3, 1), np.float32)
        d = _np(OPS["dilation2d"](x, filt, padding="SAME"))
        assert d[0, :, :, 0] == pytest.approx(np.ones((3, 3)))  # max spreads
        e = _np(OPS["erosion2d"](d, filt, padding="SAME"))
        assert e[0, 1, 1, 0] == pytest.approx(1.0)

    def test_max_pool_with_argmax_tf_indices(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1)
        v = _np(OPS["max_pool_with_argmax"](x, kernel=(2, 2), stride=(2, 2)))
        idx = _np(OPS["max_pool_with_argmax_indices"](
            x, kernel=(2, 2), stride=(2, 2)))
        np.testing.assert_allclose(v[0, :, :, 0], [[5, 7], [13, 15]])
        # TF flat index (y*W + x)*C + c
        assert idx[0, :, :, 0].tolist() == [[5, 7], [13, 15]]

    def test_col2im_is_adjoint_of_im2col(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        x = rng.normal(size=(2, 5, 5, 3)).astype(np.float32)
        cols = _np(OPS["im2col"](jnp.asarray(x), kernel=(3, 3)))
        y = rng.normal(size=cols.shape).astype(np.float32)
        back = _np(OPS["col2im"](jnp.asarray(y), input_shape=x.shape,
                                 kernel=(3, 3)))
        # <im2col(x), y> == <x, col2im(y)>
        assert float((cols * y).sum()) == pytest.approx(
            float((x * back).sum()), rel=1e-4)


class TestLossParityTail:
    def test_loss_values(self):
        p = np.array([[0.8, 0.2]], np.float32)
        y = np.array([[1.0, 0.0]], np.float32)
        assert float(_np(OPS["mae_loss"](p, y))) == pytest.approx(0.2, abs=1e-6)
        assert float(_np(OPS["mape_loss"](p, y))) > 0
        assert float(_np(OPS["kld_loss"](p, p))) == pytest.approx(0.0, abs=1e-6)
        assert float(_np(OPS["dice_loss"](y, y))) == pytest.approx(0.0, abs=1e-3)
        assert float(_np(OPS["fmeasure_loss"](y, y))) == pytest.approx(
            0.0, abs=1e-3)
        # wasserstein critic loss is just mean(pred*label)
        assert float(_np(OPS["wasserstein_loss"](p, y))) == pytest.approx(0.4)

    def test_focal_reduces_to_xent_at_gamma0(self):
        rng = np.random.default_rng(0)
        logits = rng.normal(size=(4, 3)).astype(np.float32)
        labels = (rng.random((4, 3)) < 0.5).astype(np.float32)
        focal = float(_np(OPS["focal_loss"](logits, labels, gamma=0.0,
                                            alpha=0.5)))
        bce = float(_np(OPS["multi_label_loss"](logits, labels)))
        assert focal == pytest.approx(0.5 * bce, rel=1e-4)

    def test_mixture_density_single_component_is_gaussian_nll(self):
        rng = np.random.default_rng(1)
        B, D = 3, 2
        mu = rng.normal(size=(B, D)).astype(np.float32)
        target = rng.normal(size=(B, D)).astype(np.float32)
        params = np.concatenate(
            [np.zeros((B, 1), np.float32), mu, np.zeros((B, D), np.float32)],
            axis=1)
        got = float(_np(OPS["mixture_density_loss"](params, target,
                                                    components=1)))
        want = float(np.mean(
            0.5 * np.sum((target - mu) ** 2, -1)
            + 0.5 * D * np.log(2 * np.pi)))
        assert got == pytest.approx(want, rel=1e-5)

    def test_pairwise_mse(self):
        # d = [0, 2] -> single pair (0-2)^2 = 4
        p = np.array([[1.0, 3.0]], np.float32)
        y = np.array([[1.0, 1.0]], np.float32)
        assert float(_np(OPS["mean_pairwise_squared_error"](p, y))) == \
            pytest.approx(4.0)


class TestImageAndMathTail:
    def test_colorspace_roundtrip(self):
        rng = np.random.default_rng(0)
        x = rng.random((2, 4, 4, 3)).astype(np.float32)
        for f, b in (("rgb_to_yiq", "yiq_to_rgb"), ("rgb_to_yuv", "yuv_to_rgb")):
            back = _np(OPS[b](OPS[f](x)))
            assert back == pytest.approx(x, abs=1e-5)

    def test_resize_and_upsample(self):
        x = np.arange(8, dtype=np.float32).reshape(1, 2, 2, 2)
        assert _np(OPS["resize_bilinear"](x, size=(4, 4))).shape == (1, 4, 4, 2)
        assert _np(OPS["resize_nearest"](x, size=(3, 5))).shape == (1, 3, 5, 2)
        up = _np(OPS["upsampling2d"](x, factor=(2, 2)))
        assert up.shape == (1, 4, 4, 2)
        assert up[0, 0, 0, 0] == up[0, 1, 1, 0] == x[0, 0, 0, 0]

    def test_iou(self):
        a = np.array([[0, 0, 2, 2]], np.float32)
        b = np.array([[0, 0, 2, 2], [1, 1, 3, 3], [5, 5, 6, 6]], np.float32)
        got = _np(OPS["iou"](a, b))[0]
        assert got[0] == pytest.approx(1.0)
        assert got[1] == pytest.approx(1 / 7, abs=1e-5)
        assert got[2] == pytest.approx(0.0)

    def test_norm_tail(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(2, 4, 4, 6)).astype(np.float32)
        g = np.ones((6,), np.float32)
        b = np.zeros((6,), np.float32)
        inorm = _np(OPS["instance_norm"](x, g, b))
        assert inorm.reshape(2, -1, 6).mean(1) == pytest.approx(
            np.zeros((2, 6)), abs=1e-5)
        gn = _np(OPS["group_norm"](x, g, b, groups=3))
        assert gn.shape == x.shape
        l2n = _np(OPS["l2_normalize"](x, axis=-1))
        assert np.linalg.norm(l2n, axis=-1) == pytest.approx(
            np.ones((2, 4, 4)), abs=1e-5)

    def test_attention_ops(self):
        rng = np.random.default_rng(0)
        q = rng.normal(size=(2, 3, 5, 8)).astype(np.float32)
        o = _np(OPS["dot_product_attention"](q, q, q, causal=True))
        assert o.shape == q.shape
        # causal: first query position attends only to itself
        assert o[:, :, 0] == pytest.approx(q[:, :, 0], abs=1e-5)
        x = rng.normal(size=(2, 4, 8)).astype(np.float32)
        w = [rng.normal(size=(8, 8)).astype(np.float32) / 3 for _ in range(4)]
        mh = _np(OPS["multi_head_attention"](x, *w, heads=2))
        assert mh.shape == x.shape

    def test_scatter_histogram_topk(self):
        x = np.zeros((3, 3), np.float32)
        idx = np.array([[0, 0], [2, 2]], np.int32)
        upd = np.array([5.0, 7.0], np.float32)
        out = _np(OPS["tensor_scatter_update"](x, idx, upd))
        assert out[0, 0] == 5.0 and out[2, 2] == 7.0
        h = _np(OPS["histogram_fixed_width"](
            np.array([0.0, 0.1, 0.9, 1.0], np.float32), lo=0.0, hi=1.0,
            nbins=2))
        assert h.tolist() == [2, 2]
        preds = np.array([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]], np.float32)
        t = np.array([1, 2], np.int32)
        got = _np(OPS["in_top_k"](preds, t, k=1))
        assert got.tolist() == [True, False]

    def test_math_tail(self):
        x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        assert float(_np(OPS["trace"](x))) == pytest.approx(5.0)
        assert _np(OPS["matrix_diag_part"](x)).tolist() == [1.0, 4.0]
        assert float(_np(OPS["lerp"](
            np.float32(1.0), np.float32(3.0), weight=0.5))) == 2.0
        assert float(_np(OPS["nth_element"](
            np.array([3.0, 1.0, 2.0], np.float32), n=1))) == 2.0
        assert float(_np(OPS["kth_value"](
            np.array([3.0, 1.0, 2.0], np.float32), k=1))) == 1.0
        assert _np(OPS["flatten_2d"](np.zeros((2, 3, 4)))).shape == (2, 12)
        assert float(_np(OPS["hypot"](np.float32(3.0), np.float32(4.0)))) == 5.0
        assert _np(OPS["matrix_inverse"](x)) == pytest.approx(
            np.linalg.inv(x), abs=1e-4)

    def test_registry_size_parity_floor(self):
        # SURVEY §2.1: the reference declares ~500 ops; VERDICT r3 set the
        # round-4 floor at 430
        assert len(OPS) >= 430, len(OPS)


class TestRound4Tail2:
    """numpy-parity math, linalg, signal and statistics families."""

    def test_numpy_math_tail(self):
        x = np.array([1.0, 3.0, 6.0, 10.0], np.float32)
        np.testing.assert_allclose(_np(OPS["diff"](x)), np.diff(x))
        assert float(_np(OPS["trapz"](x))) == pytest.approx(
            getattr(np, "trapezoid", np.trapz)(x))
        xp = np.array([0.0, 1.0, 2.0], np.float32)
        fp = np.array([0.0, 10.0, 20.0], np.float32)
        assert float(_np(OPS["interp"](np.float32(0.5), xp, fp))) == 5.0
        coeffs = np.array([2.0, 0.0, 1.0], np.float32)   # 2x^2 + 1
        assert float(_np(OPS["polyval"](coeffs, np.float32(3.0)))) == 19.0
        np.testing.assert_allclose(
            _np(OPS["convolve_1d"](x, np.array([1.0, 1.0], np.float32),
                                   mode="valid")),
            np.convolve(x, [1.0, 1.0], mode="valid"))
        assert _np(OPS["partition"](np.array([5., 1., 4., 2.]), kth=1))[1] \
            == 2.0
        np.testing.assert_allclose(
            _np(OPS["repeat"](np.array([1.0, 2.0]), repeats=2)),
            [1, 1, 2, 2])
        assert float(_np(OPS["cbrt"](np.float32(27.0)))) == pytest.approx(3.0)

    def test_linalg_tail(self):
        rng = np.random.default_rng(0)
        a = rng.normal(size=(4, 4)).astype(np.float32)
        spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        L = np.linalg.cholesky(spd)
        inv = _np(OPS["cholesky_inverse"](L))
        np.testing.assert_allclose(inv, np.linalg.inv(spd), atol=1e-4)
        assert float(_np(OPS["norm_fro"](a))) == pytest.approx(
            np.linalg.norm(a, "fro"), rel=1e-5)
        d = _np(OPS["diag_embed"](np.array([1.0, 2.0, 3.0], np.float32)))
        np.testing.assert_allclose(d, np.diag([1.0, 2.0, 3.0]))
        bd = _np(OPS["block_diag"](np.eye(2, dtype=np.float32),
                                   2 * np.eye(3, dtype=np.float32)))
        assert bd.shape == (5, 5) and bd[3, 3] == 2.0
        t = _np(OPS["toeplitz"](np.array([1.0, 2.0, 3.0], np.float32)))
        np.testing.assert_allclose(t[0], [1, 2, 3])
        np.testing.assert_allclose(t[:, 0], [1, 2, 3])

    def test_signal_tail(self):
        fb = _np(OPS["mel_filterbank"](n_mels=8, n_fft_bins=65,
                                       sample_rate=8000))
        assert fb.shape == (8, 65)
        assert (fb >= 0).all() and fb.max() <= 1.0
        # every filter has support, peaks ordered by frequency
        peaks = fb.argmax(1)
        assert (np.diff(peaks) > 0).all()
        s = np.array([1.0, 10.0, 100.0], np.float32)
        np.testing.assert_allclose(_np(OPS["power_to_db"](s)), [0, 10, 20],
                                   atol=1e-4)
        np.testing.assert_allclose(
            _np(OPS["db_to_power"](np.array([0.0, 10.0], np.float32))),
            [1.0, 10.0], rtol=1e-5)
        x = np.array([1.0, -1.0, 1.0, 1.0, -2.0], np.float32)
        assert int(_np(OPS["zero_crossings"](x))) == 3
        m = _np(OPS["medfilt"](np.array([1.0, 9.0, 1.0, 1.0], np.float32)))
        assert m[1] == 1.0                       # spike removed
        # detrend removes an exact linear ramp
        ramp = np.arange(10, dtype=np.float32) * 2.5 + 3.0
        np.testing.assert_allclose(_np(OPS["detrend"](ramp)),
                                   np.zeros(10), atol=1e-4)

    def test_stats_and_metrics_tail(self):
        rng = np.random.default_rng(1)
        a = rng.normal(size=200).astype(np.float32)
        b = 2.0 * a + rng.normal(0, 0.01, 200).astype(np.float32)
        assert float(_np(OPS["pearson_corr"](a, b))) == pytest.approx(
            1.0, abs=1e-3)
        assert float(_np(OPS["spearman_corr"](a, b))) == pytest.approx(
            1.0, abs=1e-2)
        sps = pytest.importorskip("scipy.stats")
        assert float(_np(OPS["skewness"](a))) == pytest.approx(
            float(sps.skew(a)), abs=1e-3)
        assert float(_np(OPS["kurtosis"](a))) == pytest.approx(
            float(sps.kurtosis(a)), abs=1e-3)
        pred = np.array([1, 1, 0, 0, 1], bool)
        lab = np.array([1, 0, 0, 1, 1], bool)
        skm = pytest.importorskip("sklearn.metrics")
        assert float(_np(OPS["f1_score"](pred, lab))) == pytest.approx(
            skm.f1_score(lab, pred), abs=1e-6)
        assert float(_np(OPS["matthews_corrcoef"](pred, lab))) == \
            pytest.approx(skm.matthews_corrcoef(lab, pred), abs=1e-6)
        assert float(_np(OPS["cohen_kappa"](pred, lab))) == pytest.approx(
            skm.cohen_kappa_score(lab, pred), abs=1e-6)
        y = rng.normal(size=50).astype(np.float32)
        yp = y + rng.normal(0, 0.1, 50).astype(np.float32)
        assert float(_np(OPS["r2_score"](yp, y))) == pytest.approx(
            skm.r2_score(y, yp), abs=1e-4)

    def test_bp_grad_ops_match_autodiff(self):
        import jax
        import jax.numpy as jnp

        rng = np.random.default_rng(2)
        x = rng.normal(size=(5,)).astype(np.float32)
        g = rng.normal(size=(5,)).astype(np.float32)
        for name, fwd in (("sigmoid_bp", jax.nn.sigmoid),
                          ("tanh_bp", jnp.tanh),
                          ("relu_bp", jax.nn.relu)):
            want = np.asarray(
                jax.vjp(fwd, jnp.asarray(x))[1](jnp.asarray(g))[0])
            np.testing.assert_allclose(_np(OPS[name](x, g)), want,
                                       atol=1e-5, err_msg=name)
        want = np.asarray(jax.vjp(
            lambda z: jax.nn.softmax(z, axis=-1), jnp.asarray(x)
        )[1](jnp.asarray(g))[0])
        np.testing.assert_allclose(_np(OPS["softmax_bp"](x, g)), want,
                                   atol=1e-5)

    def test_registry_reaches_reference_scale(self):
        # SURVEY §2.1: the reference declares ~500 ops
        assert len(OPS) >= 500, len(OPS)

    def test_review_fix_regressions(self):
        """r4 review: batched fill_diagonal, ema batch axes, tie-aware
        spearman, zero-sample crossings, validating ensure_shape."""
        sps = pytest.importorskip("scipy.stats")

        x = np.zeros((2, 3, 3), np.float32)
        fd = _np(OPS["fill_diagonal"](x, value=7.0))
        assert (fd[0].diagonal() == 7).all() and fd.sum() == 42
        e = _np(OPS["ema"](np.ones((2, 4, 5), np.float32), alpha=0.5))
        assert e.shape == (2, 4, 5)
        a = np.array([1.0, 1.0, 2.0], np.float32)
        b = np.array([1.0, 2.0, 3.0], np.float32)
        assert float(_np(OPS["spearman_corr"](a, b))) == pytest.approx(
            float(sps.spearmanr(a, b).statistic), abs=1e-6)
        assert int(_np(OPS["zero_crossings"](
            np.array([1.0, 0.0, -1.0], np.float32)))) == 1
        with pytest.raises(ValueError, match="ensure_shape"):
            OPS["ensure_shape"](np.zeros(4, np.float32), shape=(2, 2))
        # wildcard dims pass through untouched
        y = np.zeros((3, 5), np.float32)
        assert _np(OPS["ensure_shape"](y, shape=(-1, 5))).shape == (3, 5)


class TestCtcBeamSearch:
    """CTC prefix beam search (the reference's ctc_beam op): exact vs
    brute-force enumeration at full width, sane when truncated."""

    def _exact_scores(self, logp, T, C):
        """One pass over all C^T alignment paths, accumulating each
        path's collapsed sequence — O(C^T), not O(C^T x #sequences)."""
        import itertools
        from collections import defaultdict

        scores = defaultdict(float)
        for path in itertools.product(range(C), repeat=T):
            out, prev = [], -1
            p = 0.0
            for t, s in enumerate(path):
                p += logp[t, s]
                if s != prev and s != 0:
                    out.append(s)
                prev = s
            scores[tuple(out)] += np.exp(p)
        return sorted(scores.items(), key=lambda kv: -kv[1])

    def test_full_width_beam_is_exact(self):
        import jax

        rng = np.random.default_rng(0)
        T, C = 5, 3
        logits = rng.normal(0, 1.5, (1, T, C)).astype(np.float32)
        logp = np.asarray(jax.nn.log_softmax(logits[0], -1))
        ranked = self._exact_scores(logp, T, C)
        pre = _np(OPS["ctc_beam_decode"](logits, beam_width=64))
        lens = _np(OPS["ctc_beam_decode_lengths"](logits, beam_width=64))
        lps = _np(OPS["ctc_beam_decode_log_probs"](logits, beam_width=64))
        for k in range(5):
            got = tuple(int(v) for v in pre[0, k][:lens[0, k]])
            assert got == ranked[k][0], (k, got, ranked[k][0])
            assert np.exp(lps[0, k]) == pytest.approx(ranked[k][1],
                                                      abs=1e-4)

    def test_narrow_beam_top1_still_best(self):
        import jax

        rng = np.random.default_rng(3)
        T, C = 6, 4
        logits = rng.normal(0, 1.2, (2, T, C)).astype(np.float32)
        logp = np.asarray(jax.nn.log_softmax(logits[0], -1))
        ranked = self._exact_scores(logp, T, C)
        pre = _np(OPS["ctc_beam_decode"](logits, beam_width=16))
        lens = _np(OPS["ctc_beam_decode_lengths"](logits, beam_width=16))
        got = tuple(int(v) for v in pre[0, 0][:lens[0, 0]])
        assert got == ranked[0][0]
        # batched output shapes
        assert pre.shape == (2, 16, T) and lens.shape == (2, 16)

    def test_beam_beats_or_matches_greedy(self):
        rng = np.random.default_rng(4)
        logits = rng.normal(0, 1.0, (3, 8, 5)).astype(np.float32)
        beam = _np(OPS["ctc_beam_decode_log_probs"](logits, beam_width=8))
        # greedy path prob is a lower bound on the best beam's SEQUENCE prob
        import jax

        logp = np.asarray(jax.nn.log_softmax(logits, -1))
        greedy_path = logp.max(-1).sum(-1)
        assert (beam[:, 0] >= greedy_path - 1e-4).all()
