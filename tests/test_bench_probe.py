"""Unit tests for bench.py's congestion-robust timing engine — the
scoreboard machinery itself (VERDICT r3 item 1).  A scripted fake probe
stands in for the tunnel, so the acceptance logic is testable without a
chip."""

import sys
import os

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import bench  # noqa: E402


class FakeProbe:
    def __init__(self, rates):
        self._script = list(rates)
        self.rates = []

    def rate(self, calls=8):
        r = self._script.pop(0) if self._script else self.rates[-1]
        self.rates.append(r)
        return r

    @property
    def best(self):
        return max(self.rates)

    def summary(self):
        return {"n_probes": len(self.rates)}


@pytest.fixture()
def fake_probe(monkeypatch):
    def install(rates):
        p = FakeProbe(rates)
        monkeypatch.setattr(bench, "_PROBE", p)
        return p

    return install


def make_chunks(samples_each):
    """run_chunk returning a fixed sample count instantly."""
    calls = {"n": 0}

    def chunk():
        calls["n"] += 1
        return samples_each

    return chunk, calls


def test_healthy_run_stops_at_min_chunks(fake_probe):
    fake_probe([100, 99, 98, 100, 99])      # all within 20% of best
    chunk, calls = make_chunks(64)
    sps, meta = bench._timed_chunks(chunk, min_chunks=4, max_chunks=10)
    assert calls["n"] == 4
    assert meta["congested"] is False
    assert meta["chunks"] == 4
    assert meta["accepted_health"] >= 0.8
    # accepted = fastest healthy chunk
    assert meta["accepted_chunk"] == meta["chunk_rates"].index(
        max(meta["chunk_rates"]))


def test_congested_start_keeps_sampling_until_healthy(fake_probe):
    # a fast first probe sets the session best; the tunnel then slumps
    # (chunks unhealthy) and recovers — sampling must continue past
    # min_chunks until the recovered window
    fake_probe([100, 50, 50, 50, 50, 50, 99, 100])
    chunk, calls = make_chunks(10)
    sps, meta = bench._timed_chunks(chunk, min_chunks=4, max_chunks=10)
    assert calls["n"] > 4                     # kept going
    assert meta["congested"] is False         # eventually found a window
    assert meta["chunk_health"][meta["accepted_chunk"]] >= 0.8


def test_never_healthy_flags_congested(fake_probe):
    fake_probe([100] + [40] * 30)             # burst then sustained slump
    chunk, calls = make_chunks(10)
    sps, meta = bench._timed_chunks(chunk, min_chunks=4, max_chunks=6)
    assert calls["n"] == 6                    # capped
    assert meta["congested"] is True
    assert sps > 0                            # still reports the best chunk


def test_mean_rate_recorded_alongside_peak(fake_probe):
    fake_probe([100] * 12)
    chunk, _ = make_chunks(20)
    sps, meta = bench._timed_chunks(chunk, min_chunks=4)
    assert meta["samples_per_sec_mean"] > 0
    assert len(meta["chunk_rates"]) == meta["chunks"]
    assert len(meta["chunk_health"]) == meta["chunks"]
