"""Unit tests for bench.py's congestion-robust timing engine — the
scoreboard machinery itself (VERDICT r3 item 1).  A scripted fake probe
stands in for the tunnel, so the acceptance logic is testable without a
chip."""

import json
import sys
import os

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import bench  # noqa: E402


class FakeProbe:
    def __init__(self, rates):
        self._script = list(rates)
        self.rates = []

    def rate(self, calls=8):
        r = self._script.pop(0) if self._script else self.rates[-1]
        self.rates.append(r)
        return r

    @property
    def best(self):
        return max(self.rates)

    def summary(self):
        return {"n_probes": len(self.rates)}


@pytest.fixture()
def fake_probe(monkeypatch):
    def install(rates):
        p = FakeProbe(rates)
        monkeypatch.setattr(bench, "_PROBE", p)
        return p

    return install


def make_chunks(samples_each):
    """run_chunk returning a fixed sample count instantly."""
    calls = {"n": 0}

    def chunk():
        calls["n"] += 1
        return samples_each

    return chunk, calls


def test_healthy_run_stops_at_min_chunks(fake_probe):
    fake_probe([100, 99, 98, 100, 99])      # all within 20% of best
    chunk, calls = make_chunks(64)
    sps, meta = bench._timed_chunks(chunk, min_chunks=4, max_chunks=10)
    assert calls["n"] == 4
    assert meta["congested"] is False
    assert meta["chunks"] == 4
    assert meta["accepted_health"] >= 0.8
    # accepted = fastest healthy chunk
    assert meta["accepted_chunk"] == meta["chunk_rates"].index(
        max(meta["chunk_rates"]))


def test_congested_start_keeps_sampling_until_healthy(fake_probe):
    # a fast first probe sets the session best; the tunnel then slumps
    # (chunks unhealthy) and recovers — sampling must continue past
    # min_chunks until the recovered window
    fake_probe([100, 50, 50, 50, 50, 50, 99, 100])
    chunk, calls = make_chunks(10)
    sps, meta = bench._timed_chunks(chunk, min_chunks=4, max_chunks=10)
    assert calls["n"] > 4                     # kept going
    assert meta["congested"] is False         # eventually found a window
    assert meta["chunk_health"][meta["accepted_chunk"]] >= 0.8


def test_never_healthy_flags_congested(fake_probe):
    fake_probe([100] + [40] * 30)             # burst then sustained slump
    chunk, calls = make_chunks(10)
    sps, meta = bench._timed_chunks(chunk, min_chunks=4, max_chunks=6)
    assert calls["n"] == 6                    # capped
    assert meta["congested"] is True
    assert sps > 0                            # still reports the best chunk


def test_mid_chunk_stall_with_healthy_brackets_flags_congested(fake_probe):
    """r5 run-3 regression: a device-contention stall INSIDE a chunk can
    leave the crawling chunk healthy-bracketed while fast chunks sit
    between unhealthy probes — the self-contradictory window must be
    flagged congested, not published as a clean 151-sps headline."""
    # probes: chunk0 healthy (100,100) but its rate will be tiny; chunks
    # 1..3 fast but bracketed by slumped probes
    fake_probe([100, 100, 40, 40, 40, 40, 41])
    rates = iter([5, 100, 100, 100])

    def chunk():
        return next(rates)

    sps, meta = bench._timed_chunks(chunk, min_chunks=4, max_chunks=4)
    assert meta["accept_anomaly"] is True
    assert meta["congested"] is True          # evidence contradicts itself
    assert meta["accepted_health"] >= 0.8     # ...even though brackets said ok


def test_mean_rate_recorded_alongside_peak(fake_probe):
    fake_probe([100] * 12)
    chunk, _ = make_chunks(20)
    sps, meta = bench._timed_chunks(chunk, min_chunks=4)
    assert meta["samples_per_sec_mean"] > 0
    assert len(meta["chunk_rates"]) == meta["chunks"]
    assert len(meta["chunk_health"]) == meta["chunks"]


class TestUnpoisonableScoreboard:
    """VERDICT r4 #1: the canonical value field must carry a genuine TPU
    measurement or null-with-evidence — never a CPU fallback number."""

    def test_headline_value_passes_tpu_measurement(self):
        assert bench._headline_value("tpu v5 lite", 2031.0) == 2031.0
        assert bench._headline_value("TPU v4", 10.0) == 10.0

    def test_headline_value_nulls_non_tpu(self):
        assert bench._headline_value("cpu", 5.2) is None
        assert bench._headline_value("", 5.2) is None
        assert bench._headline_value(None, 5.2) is None

    def test_last_committed_tpu_record_walks_history(self):
        rec = bench._last_committed_tpu_record()
        # the repo's committed history contains round-2..4 TPU records
        # even when HEAD's BENCH_DETAILS.json is a fallback
        if rec is None:
            pytest.skip("no TPU record reachable in git history "
                        "(shallow clone?)")
        assert "tpu" in rec["device_kind"].lower()
        assert rec["resnet50_sps"] and rec["resnet50_sps"] > 100
        assert len(rec["git"]) == 12

    def test_emit_unreachable_value_is_null_with_evidence(self, tmp_path,
                                                          capsys):
        evidence = {
            "alive": False, "window_s": 600.0,
            "attempts": [{"t_s": 0.0, "outcome": "hang"},
                         {"t_s": 135.2, "outcome": "rc=1",
                          "stderr_tail": "connection refused"},
                         {"t_s": 300.0, "outcome": "hang"}],
        }
        bench._emit_unreachable(evidence, t_start=0.0,
                                out_dir=str(tmp_path))
        line = capsys.readouterr().out.strip().splitlines()[-1]
        assert len(line) < 1024
        rec = json.loads(line)
        assert rec["value"] is None
        assert rec["vs_baseline"] is None
        assert rec["extra"]["tpu_unreachable"] is True
        assert rec["extra"]["probe"]["outcomes"] == ["hang", "rc=1", "hang"]
        # the evidence block carries the chip's last committed numbers
        last = rec["extra"]["last_committed_tpu"]
        assert last and "tpu" in last["device_kind"].lower()
        # and the full record landed on disk
        details = json.loads(
            (tmp_path / "BENCH_DETAILS.json").read_text())
        assert details["tpu_unreachable"] is True
        assert details["probe"]["attempts"][1]["stderr_tail"] \
            == "connection refused"
        assert details["last_committed_tpu"] == last

    def test_await_backend_rides_out_flap(self, monkeypatch):
        import subprocess as sp

        script = iter(["hang", "rc1", "ok"])

        def fake_run(cmd, timeout=None, capture_output=None):
            step = next(script)
            if step == "hang":
                raise sp.TimeoutExpired(cmd, timeout)
            class R:
                returncode = 0 if step == "ok" else 1
                stderr = b"tunnel down"
            return R()

        monkeypatch.setattr(sp, "run", fake_run)
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        out = bench._await_backend(window_s=600.0)
        assert out["alive"] is True
        assert [a["outcome"] for a in out["attempts"]] \
            == ["hang", "rc=1", "ok"]

    def test_await_backend_gives_up_at_window_end(self, monkeypatch):
        import subprocess as sp

        clock = {"t": 0.0}

        def fake_run(cmd, timeout=None, capture_output=None):
            clock["t"] += timeout          # a hang burns its full timeout
            raise sp.TimeoutExpired(cmd, timeout)

        monkeypatch.setattr(sp, "run", fake_run)
        monkeypatch.setattr(bench.time, "time", lambda: clock["t"])
        monkeypatch.setattr(
            bench.time, "sleep",
            lambda s: clock.__setitem__("t", clock["t"] + s))
        out = bench._await_backend(window_s=600.0)
        assert out["alive"] is False
        assert len(out["attempts"]) >= 3          # kept retrying
        assert all(a["outcome"] == "hang" for a in out["attempts"])
        assert clock["t"] <= 600.0 + 120.0        # bounded
