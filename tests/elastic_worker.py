"""Worker-process script for the multi-process distributed tests.

Spawned by tests/test_distributed.py (never imported by pytest itself).
Modes, selected by DL4JTPU_TEST_MODE:

  dp_parity — join a 2-process world (2 CPU devices each), run FIXED_STEPS
      data-parallel steps of a deterministic MLP on a deterministic data
      stream, rank 0 dumps final params to DL4JTPU_TEST_OUT (npz).
  elastic — ElasticWorkerLoop-driven training with rolling checkpoints; the
      worker whose DL4JTPU_TEST_VICTIM matches its worker-id fail()s and
      dies at DL4JTPU_TEST_DIE_AT_STEP in generation 1 (fault injection at
      a step boundary — the coordinator heartbeat/evict path does the rest).
"""

import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# GLOBAL_BATCH divides every world's device count the tests use
# (2 workers x 2 devices = 4, 3 workers x 2 devices = 6)
VOCAB_IN, N_OUT, GLOBAL_BATCH, FIXED_STEPS = 12, 4, 24, 6

# read lazily so pytest can import this module for build_model/global_batch
WORKER_ID = os.environ.get("DL4JTPU_TEST_WORKER_ID", "")
COORD = os.environ.get("DL4JTPU_TEST_COORD", "")
OUT = os.environ.get("DL4JTPU_TEST_OUT", "")


def build_model():
    from deeplearning4j_tpu.nn.activations import Activation
    from deeplearning4j_tpu.nn.conf import (
        Dense,
        InputType,
        NeuralNetConfiguration,
        OutputLayer,
    )
    from deeplearning4j_tpu.nn.losses import Loss
    from deeplearning4j_tpu.nn.updaters import Sgd
    from deeplearning4j_tpu.models import SequentialModel

    conf = (
        NeuralNetConfiguration.builder()
        .seed(7)
        .updater(Sgd(0.05))
        .list()
        .layer(Dense(n_out=16, activation=Activation.TANH))
        .layer(OutputLayer(n_out=N_OUT, loss=Loss.MCXENT,
                           activation=Activation.SOFTMAX))
        .set_input_type(InputType.feed_forward(VOCAB_IN))
        .build()
    )
    return SequentialModel(conf).init()


def global_batch(step: int):
    rng = np.random.default_rng(1000 + step)
    x = rng.normal(0, 1, (GLOBAL_BATCH, VOCAB_IN)).astype(np.float32)
    y = np.eye(N_OUT, dtype=np.float32)[rng.integers(0, N_OUT, GLOBAL_BATCH)]
    return x, y


def local_shard(step: int, rank: int, world: int):
    from deeplearning4j_tpu.data.dataset import DataSet

    x, y = global_batch(step)
    per = GLOBAL_BATCH // world
    sl = slice(rank * per, (rank + 1) * per)
    return DataSet(x[sl], y[sl])


def main_dp_parity():
    from deeplearning4j_tpu.parallel import ParallelConfig, distribute
    from deeplearning4j_tpu.runtime import distributed
    from deeplearning4j_tpu.runtime.coordinator import CoordinatorClient

    client = CoordinatorClient(COORD, WORKER_ID)
    reg = client.register()
    distributed.initialize(
        distributed.DistributedConfig(
            coordinator_address=reg["jax_coordinator"],
            num_processes=reg["world"],
            process_id=reg["rank"],
            local_device_count=2,
            platform="cpu",
        )
    )
    model = build_model()
    distribute(model, ParallelConfig.data_parallel())
    router = None
    ui_url = os.environ.get("DL4JTPU_TEST_UI", "")
    if ui_url:
        # remote stats routing: every rank ships its records to the
        # chief's dashboard (RemoteUIStatsStorageRouter role)
        from deeplearning4j_tpu.ui import RemoteStatsStorageRouter, StatsListener

        router = RemoteStatsStorageRouter(ui_url)
        model.set_listeners(
            StatsListener(router, session_id=f"rank{reg['rank']}")
        )
    for step in range(FIXED_STEPS):
        model.fit_batch(local_shard(step, reg["rank"], reg["world"]))
    if router is not None:
        router.flush()
        assert router.dropped == 0, f"dropped {router.dropped} stats records"
        router.close()
    if reg["rank"] == 0 and OUT:
        from deeplearning4j_tpu.runtime.distributed import fetch_global

        flat = {
            f"{l}/{p}": fetch_global(v)
            for l, sub in model.params.items()
            for p, v in sub.items()
        }
        np.savez(OUT, **flat)
    client.leave()


def main_sharded_ckpt():
    """Multi-host sharded checkpointing: every process saves its own
    shards via orbax, then restores into a fresh distributed model and
    checks parity — no allgather anywhere."""
    from deeplearning4j_tpu.parallel import ParallelConfig, distribute
    from deeplearning4j_tpu.runtime import distributed
    from deeplearning4j_tpu.runtime.coordinator import CoordinatorClient
    from deeplearning4j_tpu.train.sharded_checkpoint import ShardedCheckpointer

    ckpt_dir = os.environ["DL4JTPU_TEST_CKPT_DIR"]
    client = CoordinatorClient(COORD, WORKER_ID)
    reg = client.register()
    distributed.initialize(
        distributed.DistributedConfig(
            coordinator_address=reg["jax_coordinator"],
            num_processes=reg["world"],
            process_id=reg["rank"],
            local_device_count=2,
            platform="cpu",
        )
    )
    model = build_model()
    distribute(model, ParallelConfig.data_parallel())
    for step in range(FIXED_STEPS):
        model.fit_batch(local_shard(step, reg["rank"], reg["world"]))
    ckpt = ShardedCheckpointer(ckpt_dir, async_save=False)
    ckpt.save(model)
    ckpt.wait()

    fresh = build_model()
    distribute(fresh, ParallelConfig.data_parallel())
    ckpt.restore_into(fresh)
    from deeplearning4j_tpu.runtime.distributed import fetch_global

    for name, sub in model.params.items():
        for pn, v in sub.items():
            a = fetch_global(v)
            b = fetch_global(fresh.params[name][pn])
            np.testing.assert_array_equal(a, b)
    assert fresh.iteration == model.iteration
    if reg["rank"] == 0 and OUT:
        with open(OUT, "w") as f:
            json.dump({"ok": True, "steps": ckpt.all_steps()}, f)
    ckpt.close()
    client.leave()


def main_elastic():
    from deeplearning4j_tpu.runtime.coordinator import CoordinatorClient
    from deeplearning4j_tpu.train.elastic import ElasticWorkerLoop

    if os.environ.get("DL4JTPU_TEST_TRACE"):
        # fleet-trace tests: record the step timeline so the final
        # metrics push carries this worker's Chrome trace to the
        # coordinator's cluster aggregator
        from deeplearning4j_tpu.observe import tracer

        tracer().enable()
    total_steps = int(os.environ["DL4JTPU_TEST_TOTAL_STEPS"])
    die_at = int(os.environ.get("DL4JTPU_TEST_DIE_AT_STEP", "-1"))
    victim = os.environ.get("DL4JTPU_TEST_VICTIM", "")
    ckpt_dir = os.environ["DL4JTPU_TEST_CKPT_DIR"]

    client = CoordinatorClient(COORD, WORKER_ID)
    loop = ElasticWorkerLoop(
        client,
        ckpt_dir,
        save_every=2,
        heartbeat_every=0.5,
        local_device_count=2,
        platform="cpu",
        jax_heartbeat_timeout_seconds=10,   # fast fail-the-world in tests
    )

    # per-step pacing for the fault-plan tests: gives the survivors'
    # heartbeat threads time to observe the abort at a STEP BOUNDARY, so
    # they exit cleanly (EXIT_MEMBERSHIP_CHANGED) instead of wedging in a
    # collective whose peer died and waiting out jax's own failure
    # detection (which this jax version exposes no timeout knob for)
    step_sleep = float(os.environ.get("DL4JTPU_TEST_STEP_SLEEP", "0") or 0)

    def on_step(model, step):
        if (
            WORKER_ID == victim
            and step + 1 == die_at
            and loop.last_registration["generation"] == 1
        ):
            # fault injection at a step boundary: tell the coordinator,
            # then die hard (no leave(), no cleanup)
            client.fail(reason="injected crash")
            os._exit(1)
        if step_sleep:
            import time

            time.sleep(step_sleep)

    model = loop.run(build_model, local_shard, total_steps, on_step=on_step)
    metrics_out = os.environ.get("DL4JTPU_TEST_METRICS_OUT", "")
    if metrics_out:
        # deterministic retry evidence under an every-Nth rpc-drop plan:
        # three consecutive retryable rpcs guarantee at least one consult
        # lands on a multiple of N<=3, forcing a retry that the policy
        # absorbs — so dl4jtpu_rpc_retries_total is provably non-zero
        for _ in range(3):
            client.status()
        from deeplearning4j_tpu.observe.metrics import registry

        with open(f"{metrics_out}.{WORKER_ID}.{os.getpid()}", "w") as f:
            f.write(registry().to_prometheus_text())
    if OUT:
        with open(OUT, "a") as f:
            f.write(json.dumps({
                "worker": WORKER_ID,
                "generation": loop.last_registration["generation"],
                "world": loop.last_registration["world"],
                "final_iteration": model.iteration,
                "score": float(model.score_value),
            }) + "\n")


if __name__ == "__main__":
    MODE = os.environ["DL4JTPU_TEST_MODE"]
    if MODE == "dp_parity":
        main_dp_parity()
    elif MODE == "sharded_ckpt":
        main_sharded_ckpt()
    elif MODE == "elastic":
        main_elastic()
    else:
        raise SystemExit(f"unknown mode {MODE}")
