"""Zoo model construction + forward-shape tests (small input sizes on CPU)."""

import numpy as np
import pytest

from deeplearning4j_tpu.zoo import LeNet, ResNet50, SimpleCNN, UNet, VGG16


def test_lenet_builds_and_forwards():
    model = LeNet().init_model()
    x = np.zeros((2, 28, 28, 1), np.float32)
    out = model.output(x)
    assert out.shape == (2, 10)
    np.testing.assert_allclose(np.asarray(out).sum(axis=1), 1.0, rtol=1e-4)


def test_resnet50_small_builds_and_forwards():
    model = ResNet50(num_classes=10, height=64, width=64, channels=3).init_model()
    # 53 conv layers in the bottleneck stack + stem
    n_convs = sum(1 for n in model.conf.nodes if type(n.layer).__name__ == "Conv2D")
    assert n_convs >= 53
    x = np.zeros((2, 64, 64, 3), np.float32)
    out = model.output(x)
    assert out.shape == (2, 10)
    np.testing.assert_allclose(np.asarray(out).sum(axis=1), 1.0, rtol=1e-3)


def test_resnet50_trains_one_step():
    from deeplearning4j_tpu.data import DataSet

    model = ResNet50(num_classes=4, height=32, width=32, channels=3).init_model()
    x = np.random.default_rng(0).normal(size=(8, 32, 32, 3)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[np.arange(8) % 4]
    model.fit_batch(DataSet(x, y))
    s1 = model.score_value
    assert np.isfinite(s1)


def test_vgg16_builds():
    model = VGG16(num_classes=10, height=32, width=32, channels=3, fc_width=64).init_model()
    x = np.zeros((2, 32, 32, 3), np.float32)
    out = model.output(x)
    assert out.shape == (2, 10)


def test_simplecnn_builds():
    model = SimpleCNN(num_classes=5, height=48, width=48, channels=3).init_model()
    out = model.output(np.zeros((2, 48, 48, 3), np.float32))
    assert out.shape == (2, 5)


def test_unet_builds_and_segments():
    model = UNet(num_classes=1, height=32, width=32, channels=3,
                 base_filters=4, depth=2).init_model()
    x = np.zeros((2, 32, 32, 3), np.float32)
    out = model.output(x)
    assert out.shape == (2, 32, 32, 1)
    arr = np.asarray(out)
    assert np.all((arr >= 0) & (arr <= 1))  # sigmoid segmentation map


def test_unet_train_step():
    from deeplearning4j_tpu.data import DataSet

    model = UNet(num_classes=1, height=16, width=16, channels=1,
                 base_filters=2, depth=2).init_model()
    x = np.random.default_rng(0).normal(size=(2, 16, 16, 1)).astype(np.float32)
    y = (x > 0).astype(np.float32)
    model.fit_batch(DataSet(x, y))
    assert np.isfinite(model.score_value)
