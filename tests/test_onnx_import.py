"""ONNX import tests — golden-file pattern (SURVEY.md §4.1 "TF import
regression suite" applied to ONNX): build real serialized .onnx bytes,
import into SameDiff, execute, and compare against goldens computed with
torch (NCHW-native — an independent implementation, which cross-checks the
importer's NCHW->NHWC boundary handling) or numpy."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

from onnx_fixtures import make_model, make_node
from deeplearning4j_tpu.modelimport.onnx import ONNXImportError, import_onnx

RNG = np.random.default_rng(7)


def run(sd, feeds):
    outs = sd.output(feeds, *sd.onnx_outputs)
    if len(sd.onnx_outputs) == 1:
        return [np.asarray(outs)]
    return [np.asarray(o) for o in outs]


class TestMLP:
    def test_gemm_relu_softmax_matches_numpy(self):
        W1 = RNG.normal(0, 0.5, (4, 8)).astype(np.float32)
        b1 = RNG.normal(0, 0.1, (8,)).astype(np.float32)
        W2 = RNG.normal(0, 0.5, (8, 3)).astype(np.float32)
        b2 = RNG.normal(0, 0.1, (3,)).astype(np.float32)
        model = make_model(
            [
                make_node("Gemm", ["x", "W1", "b1"], ["h"]),
                make_node("Relu", ["h"], ["hr"]),
                make_node("Gemm", ["hr", "W2", "b2"], ["logits"]),
                make_node("Softmax", ["logits"], ["probs"], axis=-1),
            ],
            inputs=[("x", (2, 4))],
            outputs=["probs"],
            initializers={"W1": W1, "b1": b1, "W2": W2, "b2": b2},
        )
        sd = import_onnx(model)
        x = RNG.normal(0, 1, (2, 4)).astype(np.float32)
        (probs,) = run(sd, {"x": x})
        h = np.maximum(x @ W1 + b1, 0)
        logits = h @ W2 + b2
        e = np.exp(logits - logits.max(-1, keepdims=True))
        np.testing.assert_allclose(probs, e / e.sum(-1, keepdims=True),
                                   rtol=1e-5, atol=1e-6)

    def test_gemm_transB_alpha_beta(self):
        A = RNG.normal(0, 1, (3, 4)).astype(np.float32)
        Wt = RNG.normal(0, 1, (5, 4)).astype(np.float32)   # transB layout
        C = RNG.normal(0, 1, (5,)).astype(np.float32)
        model = make_model(
            [make_node("Gemm", ["x", "W", "C"], ["y"],
                       alpha=2.0, beta=0.5, transB=1)],
            inputs=[("x", (3, 4))], outputs=["y"],
            initializers={"W": Wt, "C": C},
        )
        (y,) = run(import_onnx(model), {"x": A})
        np.testing.assert_allclose(y, 2.0 * (A @ Wt.T) + 0.5 * C,
                                   rtol=1e-5, atol=1e-5)


class TestCNN:
    def test_conv_pool_flatten_matches_torch(self):
        x = RNG.normal(0, 1, (2, 3, 8, 8)).astype(np.float32)
        W = RNG.normal(0, 0.3, (6, 3, 3, 3)).astype(np.float32)  # OIHW
        b = RNG.normal(0, 0.1, (6,)).astype(np.float32)
        Wd = RNG.normal(0, 0.3, (6 * 2 * 2, 4)).astype(np.float32)
        model = make_model(
            [
                make_node("Conv", ["x", "W", "b"], ["c"],
                          kernel_shape=[3, 3], strides=[1, 1],
                          pads=[1, 1, 1, 1]),
                make_node("Relu", ["c"], ["cr"]),
                make_node("MaxPool", ["cr"], ["p"],
                          kernel_shape=[2, 2], strides=[2, 2]),
                make_node("AveragePool", ["p"], ["a"],
                          kernel_shape=[2, 2], strides=[2, 2]),
                make_node("Flatten", ["a"], ["f"]),
                make_node("MatMul", ["f", "Wd"], ["y"]),
            ],
            inputs=[("x", (2, 3, 8, 8))], outputs=["y"],
            initializers={"W": W, "b": b, "Wd": Wd},
        )
        (y,) = run(import_onnx(model), {"x": x})

        t = torch.from_numpy
        c = F.relu(F.conv2d(t(x), t(W), t(b), stride=1, padding=1))
        p = F.max_pool2d(c, 2, 2)
        a = F.avg_pool2d(p, 2, 2)
        expected = a.flatten(1).numpy() @ Wd
        np.testing.assert_allclose(y, expected, rtol=1e-4, atol=1e-5)

    def test_batchnorm_global_pool_matches_torch(self):
        x = RNG.normal(0, 1, (2, 4, 6, 6)).astype(np.float32)
        gamma = RNG.normal(1, 0.1, (4,)).astype(np.float32)
        beta = RNG.normal(0, 0.1, (4,)).astype(np.float32)
        mean = RNG.normal(0, 0.5, (4,)).astype(np.float32)
        var = RNG.uniform(0.5, 2.0, (4,)).astype(np.float32)
        model = make_model(
            [
                make_node("BatchNormalization",
                          ["x", "gamma", "beta", "mean", "var"], ["bn"],
                          epsilon=1e-5),
                make_node("GlobalAveragePool", ["bn"], ["g"]),
            ],
            inputs=[("x", (2, 4, 6, 6))], outputs=["g"],
            initializers={"gamma": gamma, "beta": beta,
                          "mean": mean, "var": var},
        )
        (g,) = run(import_onnx(model), {"x": x})
        bn = F.batch_norm(torch.from_numpy(x), torch.from_numpy(mean),
                          torch.from_numpy(var), torch.from_numpy(gamma),
                          torch.from_numpy(beta), training=False, eps=1e-5)
        expected = bn.mean(dim=(2, 3), keepdim=True).numpy()
        np.testing.assert_allclose(g, expected, rtol=1e-4, atol=1e-5)

    def test_depthwise_conv_matches_torch(self):
        x = RNG.normal(0, 1, (1, 4, 6, 6)).astype(np.float32)
        W = RNG.normal(0, 0.3, (4, 1, 3, 3)).astype(np.float32)
        model = make_model(
            [make_node("Conv", ["x", "W"], ["y"], kernel_shape=[3, 3],
                       strides=[1, 1], pads=[1, 1, 1, 1], group=4)],
            inputs=[("x", (1, 4, 6, 6))], outputs=["y"],
            initializers={"W": W},
        )
        (y,) = run(import_onnx(model), {"x": x})
        expected = F.conv2d(torch.from_numpy(x), torch.from_numpy(W),
                            stride=1, padding=1, groups=4).numpy()
        np.testing.assert_allclose(y, expected, rtol=1e-4, atol=1e-5)


class TestTransformerBlock:
    def test_decomposed_attention_block_matches_torch(self):
        """Single-head self-attention + LayerNorm + Erf-GELU FFN — the
        BERT-block decomposition torch exporters emit."""
        B, T, D = 2, 5, 8
        x = RNG.normal(0, 1, (B, T, D)).astype(np.float32)
        Wq, Wk, Wv, Wo = (RNG.normal(0, 0.4, (D, D)).astype(np.float32)
                          for _ in range(4))
        g1 = RNG.normal(1, 0.1, (D,)).astype(np.float32)
        b1 = RNG.normal(0, 0.1, (D,)).astype(np.float32)
        W1 = RNG.normal(0, 0.4, (D, 2 * D)).astype(np.float32)
        W2 = RNG.normal(0, 0.4, (2 * D, D)).astype(np.float32)
        scale = np.float32(1.0 / np.sqrt(D))
        half, one = np.float32(0.5), np.float32(1.0)
        isqrt2 = np.float32(1.0 / np.sqrt(2.0))

        nodes = [
            make_node("MatMul", ["x", "Wq"], ["q"]),
            make_node("MatMul", ["x", "Wk"], ["k"]),
            make_node("MatMul", ["x", "Wv"], ["v"]),
            make_node("Transpose", ["k"], ["kT"], perm=[0, 2, 1]),
            make_node("MatMul", ["q", "kT"], ["scores"]),
            make_node("Mul", ["scores", "scale"], ["scaled"]),
            make_node("Softmax", ["scaled"], ["attn"], axis=-1),
            make_node("MatMul", ["attn", "v"], ["ctx"]),
            make_node("MatMul", ["ctx", "Wo"], ["proj"]),
            make_node("Add", ["x", "proj"], ["res1"]),
            make_node("LayerNormalization", ["res1", "g1", "b1"], ["ln1"],
                      epsilon=1e-5, axis=-1),
            # Erf-GELU: 0.5 * h * (1 + erf(h / sqrt(2)))
            make_node("MatMul", ["ln1", "W1"], ["h"]),
            make_node("Mul", ["h", "isqrt2"], ["hs"]),
            make_node("Erf", ["hs"], ["eh"]),
            make_node("Add", ["eh", "one"], ["e1"]),
            make_node("Mul", ["h", "e1"], ["he"]),
            make_node("Mul", ["he", "half"], ["gelu"]),
            make_node("MatMul", ["gelu", "W2"], ["ffn"]),
            make_node("Add", ["ln1", "ffn"], ["out"]),
        ]
        model = make_model(
            nodes, inputs=[("x", (B, T, D))], outputs=["out"],
            initializers={"Wq": Wq, "Wk": Wk, "Wv": Wv, "Wo": Wo,
                          "g1": g1, "b1": b1, "W1": W1, "W2": W2,
                          "scale": scale, "half": half, "one": one,
                          "isqrt2": isqrt2},
        )
        (out,) = run(import_onnx(model), {"x": x})

        tx = torch.from_numpy(x)
        q, k, v = tx @ torch.from_numpy(Wq), tx @ torch.from_numpy(Wk), tx @ torch.from_numpy(Wv)
        attn = torch.softmax(q @ k.transpose(1, 2) * float(scale), dim=-1)
        res1 = tx + (attn @ v) @ torch.from_numpy(Wo)
        ln1 = F.layer_norm(res1, (D,), torch.from_numpy(g1),
                           torch.from_numpy(b1), eps=1e-5)
        h = ln1 @ torch.from_numpy(W1)
        gelu = 0.5 * h * (1 + torch.erf(h / np.sqrt(2.0)))
        expected = (ln1 + gelu @ torch.from_numpy(W2)).numpy()
        np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


class TestImportSemantics:
    def _mlp_bytes(self):
        W = RNG.normal(0, 0.5, (4, 3)).astype(np.float32)
        return make_model(
            [make_node("MatMul", ["x", "W"], ["y"])],
            inputs=[("x", (2, 4))], outputs=["y"],
            initializers={"W": W},
        ), W

    def test_path_and_bytes_entry(self, tmp_path):
        data, W = self._mlp_bytes()
        p = tmp_path / "m.onnx"
        p.write_bytes(data)
        x = RNG.normal(0, 1, (2, 4)).astype(np.float32)
        (y1,) = run(import_onnx(str(p)), {"x": x})
        (y2,) = run(import_onnx(data), {"x": x})
        np.testing.assert_allclose(y1, x @ W, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(y1, y2)

    def test_facade_entry_point(self):
        from deeplearning4j_tpu.modelimport.tensorflow import import_onnx as f

        data, W = self._mlp_bytes()
        sd = f(data)
        assert sd.onnx_outputs == ["y"]

    def test_trainable_promotes_float_initializers(self):
        data, W = self._mlp_bytes()
        sd = import_onnx(data, trainable=True)
        assert "W" in sd.variables()

    def test_unmapped_op_raises_with_name(self):
        data = make_model(
            [make_node("STFT", ["x"], ["y"])],
            inputs=[("x", (2, 4))], outputs=["y"],
        )
        with pytest.raises(ONNXImportError, match="STFT"):
            import_onnx(data)

    def test_dynamic_reshape_raises(self):
        data = make_model(
            [
                make_node("Relu", ["x"], ["shape_src"]),
                make_node("Reshape", ["x", "shape_src"], ["y"]),
            ],
            inputs=[("x", (2, 4))], outputs=["y"],
        )
        with pytest.raises(ONNXImportError, match="compile-time constant"):
            import_onnx(data)

    def test_slice_negative_ends_and_axes(self):
        data = make_model(
            [make_node("Slice", ["x", "starts", "ends", "axes"], ["y"])],
            inputs=[("x", (2, 5))], outputs=["y"],
            initializers={"starts": np.asarray([1], np.int64),
                          "ends": np.asarray([-1], np.int64),
                          "axes": np.asarray([-1], np.int64)},
        )
        x = np.arange(10, dtype=np.float32).reshape(2, 5)
        (y,) = run(import_onnx(data), {"x": x})
        np.testing.assert_allclose(y, x[:, 1:-1])     # NOT x[:, 1:]

    def test_tied_weights_promote_to_one_var(self):
        W = RNG.normal(0, 0.5, (4, 4)).astype(np.float32)
        data = make_model(
            [
                make_node("Identity", ["W"], ["W2"]),
                make_node("MatMul", ["x", "W"], ["h"]),
                make_node("MatMul", ["h", "W2"], ["y"]),
            ],
            inputs=[("x", (2, 4))], outputs=["y"],
            initializers={"W": W},
        )
        sd = import_onnx(data, trainable=True)
        assert len(sd.variables()) == 1        # tied, not drifting copies

    def test_constant_graph_output_allowed(self):
        data = make_model(
            [make_node("Constant", [], ["c"],
                       value=np.asarray([1.0, 2.0], np.float32)),
             make_node("Relu", ["x"], ["r"])],
            inputs=[("x", (2,))], outputs=["r", "c"],
        )
        sd = import_onnx(data)
        r, c = run(sd, {"x": np.asarray([-1.0, 3.0], np.float32)})
        np.testing.assert_allclose(c, [1.0, 2.0])

    def test_ceil_mode_and_same_lower_raise(self):
        pool = make_model(
            [make_node("MaxPool", ["x"], ["y"], kernel_shape=[3, 3],
                       strides=[2, 2], ceil_mode=1)],
            inputs=[("x", (1, 1, 7, 7))], outputs=["y"],
        )
        with pytest.raises(ONNXImportError, match="ceil_mode"):
            import_onnx(pool)
        conv = make_model(
            [make_node("Conv", ["x", "W"], ["y"], kernel_shape=[2, 2],
                       auto_pad="SAME_LOWER")],
            inputs=[("x", (1, 1, 4, 4))], outputs=["y"],
            initializers={"W": RNG.normal(0, 1, (1, 1, 2, 2)).astype(np.float32)},
        )
        with pytest.raises(ONNXImportError, match="SAME_LOWER"):
            import_onnx(conv)

    def test_onnx_reshape_zero_copies_dim(self):
        data = make_model(
            [make_node("Reshape", ["x", "shape"], ["y"])],
            inputs=[("x", (2, 3, 4))], outputs=["y"],
            initializers={"shape": np.asarray([0, 12], np.int64)},
        )
        x = RNG.normal(0, 1, (2, 3, 4)).astype(np.float32)
        (y,) = run(import_onnx(data), {"x": x})
        assert y.shape == (2, 12)


class TestOpsetBreadth:
    def test_elementwise_trig_chain(self):
        x = RNG.normal(0, 1, (3, 4)).astype(np.float32)
        m = make_model(
            [
                make_node("Sin", ["x"], ["s"]),
                make_node("Cos", ["x"], ["c"]),
                make_node("Add", ["s", "c"], ["sc"]),
                make_node("Floor", ["sc"], ["f"]),
                make_node("Sign", ["f"], ["y"]),
            ],
            inputs=[("x", x.shape)], outputs=["y"],
        )
        (got,) = run(import_onnx(m), {"x": x})
        np.testing.assert_allclose(
            got, np.sign(np.floor(np.sin(x) + np.cos(x))), atol=1e-6
        )

    def test_hardsigmoid_hardswish_prelu(self):
        x = RNG.normal(0, 2, (4, 5)).astype(np.float32)
        slope = np.full((5,), 0.1, np.float32)
        m = make_model(
            [
                make_node("HardSigmoid", ["x"], ["hs"], alpha=0.2, beta=0.5),
                make_node("HardSwish", ["x"], ["hw"]),
                make_node("PRelu", ["x", "slope"], ["pr"]),
            ],
            inputs=[("x", x.shape)], outputs=["hs", "hw", "pr"],
            initializers={"slope": slope},
        )
        hs, hw, pr = run(import_onnx(m), {"x": x})
        np.testing.assert_allclose(
            hs, np.clip(0.2 * x + 0.5, 0, 1), atol=1e-6)
        np.testing.assert_allclose(
            hw, np.asarray(torch.nn.functional.hardswish(torch.tensor(x))),
            atol=1e-5)
        np.testing.assert_allclose(
            pr, np.where(x >= 0, x, 0.1 * x), atol=1e-6)

    def test_reductions_and_argmax(self):
        x = RNG.normal(0, 1, (3, 6)).astype(np.float32)
        m = make_model(
            [
                make_node("ReduceL2", ["x"], ["l2"], axes=[1], keepdims=0),
                make_node("ReduceProd", ["x"], ["pr"], axes=[1], keepdims=0),
                make_node("ReduceLogSumExp", ["x"], ["lse"], axes=[1],
                          keepdims=0),
                make_node("ArgMax", ["x"], ["am"], axis=1, keepdims=0),
            ],
            inputs=[("x", x.shape)], outputs=["l2", "pr", "lse", "am"],
        )
        l2, pr, lse, am = run(import_onnx(m), {"x": x})
        np.testing.assert_allclose(l2, np.linalg.norm(x, axis=1), atol=1e-5)
        np.testing.assert_allclose(pr, np.prod(x, axis=1), rtol=1e-5)
        np.testing.assert_allclose(
            lse, np.log(np.exp(x).sum(axis=1)), atol=1e-5)
        np.testing.assert_array_equal(am, x.argmax(axis=1))

    def test_split_expand_range_constantofshape(self):
        x = RNG.normal(0, 1, (2, 6)).astype(np.float32)
        m = make_model(
            [
                make_node("Split", ["x"], ["a", "b"], axis=1, split=[2, 4]),
                make_node("Expand", ["a", "eshape"], ["e"]),
                make_node("Range", ["r0", "r1", "r2"], ["rg"]),
                make_node("ConstantOfShape", ["cshape"], ["cf"],
                          value=np.array([3.0], np.float32)),
            ],
            inputs=[("x", x.shape)], outputs=["e", "b", "rg", "cf"],
            initializers={
                "eshape": np.array([2, 2, 2], np.int64),
                "r0": np.array(0.0, np.float32),
                "r1": np.array(5.0, np.float32),
                "r2": np.array(2.0, np.float32),
                "cshape": np.array([2, 3], np.int64),
            },
        )
        e, b, rg, cf = run(import_onnx(m), {"x": x})
        np.testing.assert_allclose(b, x[:, 2:], atol=1e-6)
        assert e.shape == (2, 2, 2)
        np.testing.assert_allclose(rg, [0.0, 2.0, 4.0])
        np.testing.assert_allclose(cf, np.full((2, 3), 3.0))

    def test_lrn_matches_torch(self):
        x = RNG.normal(0, 1, (2, 8, 5, 5)).astype(np.float32)
        m = make_model(
            [make_node("LRN", ["x"], ["y"], size=3, alpha=2e-4, beta=0.75,
                       bias=1.5)],
            inputs=[("x", x.shape)], outputs=["y"],
        )
        (got,) = run(import_onnx(m), {"x": x})
        want = torch.nn.LocalResponseNorm(3, alpha=2e-4, beta=0.75, k=1.5)(
            torch.tensor(x)
        ).numpy()
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_instance_norm_matches_torch(self):
        x = RNG.normal(0, 1, (2, 4, 6, 6)).astype(np.float32)
        scale = RNG.normal(1, 0.2, (4,)).astype(np.float32)
        bias = RNG.normal(0, 0.2, (4,)).astype(np.float32)
        m = make_model(
            [make_node("InstanceNormalization", ["x", "s", "b"], ["y"],
                       epsilon=1e-5)],
            inputs=[("x", x.shape)], outputs=["y"],
            initializers={"s": scale, "b": bias},
        )
        (got,) = run(import_onnx(m), {"x": x})
        want = F.instance_norm(
            torch.tensor(x), weight=torch.tensor(scale),
            bias=torch.tensor(bias), eps=1e-5,
        ).numpy()
        np.testing.assert_allclose(got, want, atol=1e-4)

    def test_conv_transpose_matches_torch(self):
        x = RNG.normal(0, 1, (1, 3, 5, 5)).astype(np.float32)
        w = RNG.normal(0, 0.3, (3, 4, 2, 2)).astype(np.float32)  # (I,O,kH,kW)
        m = make_model(
            [make_node("ConvTranspose", ["x", "w"], ["y"], strides=[2, 2])],
            inputs=[("x", x.shape)], outputs=["y"],
            initializers={"w": w},
        )
        (got,) = run(import_onnx(m), {"x": x})
        want = F.conv_transpose2d(
            torch.tensor(x), torch.tensor(w), stride=2
        ).numpy()
        np.testing.assert_allclose(got, want, atol=1e-4)

    def test_resize_nearest_and_topk(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        m = make_model(
            [
                make_node("Resize", ["x", "", "", "sizes"], ["y"],
                          mode="nearest",
                          coordinate_transformation_mode="asymmetric"),
            ],
            inputs=[("x", x.shape)], outputs=["y"],
            initializers={"sizes": np.array([1, 1, 8, 8], np.int64)},
        )
        (got,) = run(import_onnx(m), {"x": x})
        want = F.interpolate(torch.tensor(x), size=(8, 8), mode="nearest").numpy()
        np.testing.assert_allclose(got, want, atol=1e-5)

        t = RNG.normal(0, 1, (3, 7)).astype(np.float32)
        m2 = make_model(
            [make_node("TopK", ["t", "k"], ["v", "i"], axis=-1)],
            inputs=[("t", t.shape)], outputs=["v", "i"],
            initializers={"k": np.array([3], np.int64)},
        )
        v, i = run(import_onnx(m2), {"t": t})
        tv, ti = torch.topk(torch.tensor(t), 3, dim=-1)
        np.testing.assert_allclose(v, tv.numpy(), atol=1e-6)
        np.testing.assert_array_equal(i, ti.numpy())

    def test_logical_and_mod(self):
        a = np.array([1.0, 0.0, 1.0], np.float32)
        b = np.array([1.0, 1.0, 0.0], np.float32)
        x = np.array([7.0, -7.0, 5.0], np.float32)
        y = np.array([3.0, 3.0, 2.0], np.float32)
        m = make_model(
            [
                make_node("And", ["a", "b"], ["and_"]),
                make_node("Xor", ["a", "b"], ["xor_"]),
                make_node("Mod", ["x", "y"], ["fm"], fmod=1),
                make_node("Mod", ["x", "y"], ["im"]),
                make_node("GreaterOrEqual", ["x", "y"], ["ge"]),
            ],
            inputs=[("a", a.shape), ("b", b.shape), ("x", x.shape),
                    ("y", y.shape)],
            outputs=["and_", "xor_", "fm", "im", "ge"],
        )
        and_, xor_, fm, im, ge = run(
            import_onnx(m), {"a": a, "b": b, "x": x, "y": y})
        np.testing.assert_allclose(and_, [1.0, 0.0, 0.0])
        np.testing.assert_allclose(xor_, [0.0, 1.0, 1.0])
        np.testing.assert_allclose(fm, np.fmod(x, y), atol=1e-6)
        np.testing.assert_allclose(im, np.mod(x, y), atol=1e-6)
        np.testing.assert_allclose(ge, (x >= y).astype(np.float32))


class TestOnnxControlFlow:
    """ONNX If/Loop subgraphs -> lax.cond / lax.while_loop (round 4 —
    closes the §2.2 import control-flow gap on the ONNX side)."""

    def test_if_both_branches(self):
        import numpy as np

        from onnx_fixtures import make_graph, make_model, make_node

        then_g = make_graph(
            [make_node("Mul", ["x", "two"], ["tout"])],
            [], ["tout"], initializers={"two": np.float32(2.0)},
            name="then",
        )
        else_g = make_graph(
            [make_node("Sub", ["x", "three"], ["eout"])],
            [], ["eout"], initializers={"three": np.float32(3.0)},
            name="else",
        )
        raw = make_model(
            [
                make_node("ReduceSum", ["x"], ["s"], keepdims=0),
                make_node("Constant", [], ["zero"], value=np.float32(0.0)),
                make_node("Greater", ["s", "zero"], ["pred"]),
                make_node("If", ["pred"], ["y"], then_branch=then_g,
                          else_branch=else_g),
            ],
            [("x", (4,))], ["y"],
        )
        sd = import_onnx(raw)
        xp = np.array([1.0, 2.0, -0.5, 0.25], np.float32)
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": xp}, "y")), xp * 2.0, atol=1e-6)
        xn = -xp
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": xn}, "y")), xn - 3.0, atol=1e-6)

    def test_loop_trip_count(self):
        import numpy as np

        from onnx_fixtures import make_graph, make_model, make_node

        # body: (iter, cond, v) -> (cond, v * 2 + 1)
        body = make_graph(
            [
                make_node("Mul", ["v", "two"], ["v2"]),
                make_node("Add", ["v2", "one"], ["v_out"]),
                make_node("Identity", ["cond_in"], ["cond_out"]),
            ],
            ["iter_num", "cond_in", "v"], ["cond_out", "v_out"],
            initializers={"two": np.float32(2.0), "one": np.float32(1.0)},
            name="body",
        )
        raw = make_model(
            [make_node("Loop", ["M", "cond0", "x"], ["y"], body=body)],
            [("x", (3,))], ["y"],
            initializers={"M": np.int64(5), "cond0": np.bool_(True)},
        )
        sd = import_onnx(raw)
        xp = np.array([0.0, 1.0, -1.0], np.float32)
        want = xp.copy()
        for _ in range(5):
            want = want * 2 + 1
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": xp}, "y")), want, atol=1e-5)

    def test_loop_static_trip_differentiates(self):
        """Round 5: a static trip-count input M bounds the Loop by its
        own semantics, so it lowers to lax.scan — reverse-mode
        differentiable (fine-tuning through imported Loop bodies)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from onnx_fixtures import make_graph, make_model, make_node

        body = make_graph(
            [
                make_node("Mul", ["v", "two"], ["v_out"]),
                make_node("Identity", ["cond_in"], ["cond_out"]),
            ],
            ["iter_num", "cond_in", "v"], ["cond_out", "v_out"],
            initializers={"two": np.float32(2.0)},
            name="body",
        )
        raw = make_model(
            [make_node("Loop", ["M", "cond0", "x"], ["y"], body=body)],
            [("x", (2,))], ["y"],
            initializers={"M": np.int64(4), "cond0": np.bool_(True)},
        )
        sd = import_onnx(raw)
        (w,) = [n for n in sd._ops if n.op == "_while"]
        assert w.attrs["max_trip"] == 4
        xp = np.array([1.0, -2.0], np.float32)
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": xp}, "y")), xp * 16, atol=1e-5)

        def f(xval):
            (o,) = sd._execute({**sd._values, "x": xval}, ("y",))
            return jnp.sum(o)

        g = jax.grad(f)(jnp.asarray(xp))
        np.testing.assert_allclose(np.asarray(g), [16.0, 16.0], rtol=1e-6)

    def test_loop_mid_range_m_keeps_termination_check(self):
        """M in (scan cap, INT32_MAX] is a REAL bound, not the torch
        cond-only-while idiom: it must stay an i < M check on the
        while_loop lowering — a cond that never goes false must still
        terminate at M (ADVICE.md: the old code dropped the bound for
        any M beyond the cap, turning these into infinite loops)."""
        import numpy as np

        from onnx_fixtures import make_graph, make_model, make_node

        # body: v = v + 1, cond stays True forever — only i < M stops it
        body = make_graph(
            [
                make_node("Add", ["v", "one"], ["v_out"]),
                make_node("Identity", ["cond_in"], ["cond_out"]),
            ],
            ["iter_num", "cond_in", "v"], ["cond_out", "v_out"],
            initializers={"one": np.float32(1.0)},
            name="body",
        )
        m_val = 20000                      # > _LOOP_SCAN_CAP, << INT32_MAX
        raw = make_model(
            [make_node("Loop", ["M", "cond0", "x"], ["y"], body=body)],
            [("x", (1,))], ["y"],
            initializers={"M": np.int64(m_val), "cond0": np.bool_(True)},
        )
        sd = import_onnx(raw)
        (w,) = [n for n in sd._ops if n.op == "_while"]
        assert w.attrs.get("max_trip") is None     # while_loop, not scan
        xp = np.array([0.0], np.float32)
        got = np.asarray(sd.output({"x": xp}, "y"))
        np.testing.assert_allclose(got, [float(m_val)], atol=0)

    def test_loop_huge_m_keeps_while_lowering(self):
        """torch exports cond-only while-loops with M=INT64_MAX; such an
        M must NOT become a scan length (r5 review finding)."""
        import numpy as np

        from onnx_fixtures import make_graph, make_model, make_node

        body = make_graph(
            [
                make_node("Mul", ["v", "half"], ["v_out"]),
                make_node("ReduceSum", ["v_out"], ["s"], keepdims=0),
                make_node("Greater", ["s", "thresh"], ["cond_out"]),
            ],
            ["iter_num", "cond_in", "v"], ["cond_out", "v_out"],
            initializers={"half": np.float32(0.5),
                          "thresh": np.float32(0.1)},
            name="body",
        )
        raw = make_model(
            [make_node("Loop", ["M", "cond0", "x"], ["y"], body=body)],
            [("x", (2,))], ["y"],
            initializers={"M": np.int64(2 ** 62),
                          "cond0": np.bool_(True)},
        )
        sd = import_onnx(raw)
        (w,) = [n for n in sd._ops if n.op == "_while"]
        assert w.attrs.get("max_trip") is None
        xp = np.array([4.0, 4.0], np.float32)
        got = np.asarray(sd.output({"x": xp}, "y"))
        want = xp.copy()
        while want.sum() > 0.1:
            want = want * 0.5
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_loop_with_outer_capture(self):
        import numpy as np

        from onnx_fixtures import make_graph, make_model, make_node

        # body captures the OUTER tensor "step" by name
        body = make_graph(
            [
                make_node("Add", ["v", "step"], ["v_out"]),
                make_node("Identity", ["cond_in"], ["cond_out"]),
            ],
            ["iter_num", "cond_in", "v"], ["cond_out", "v_out"],
            name="body",
        )
        raw = make_model(
            [
                make_node("Add", ["s0", "s0"], ["step"]),
                make_node("Loop", ["M", "cond0", "x"], ["y"], body=body),
            ],
            [("x", (2,)), ("s0", (2,))], ["y"],
            initializers={"M": np.int64(3), "cond0": np.bool_(True)},
        )
        sd = import_onnx(raw)
        xp = np.array([1.0, 2.0], np.float32)
        s0 = np.array([0.5, -0.5], np.float32)
        want = xp + 3 * (2 * s0)
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": xp, "s0": s0}, "y")), want,
            atol=1e-6)

    def test_loop_scan_outputs_rejected(self):
        import numpy as np
        import pytest

        from onnx_fixtures import make_graph, make_model, make_node

        body = make_graph(
            [
                make_node("Identity", ["cond_in"], ["cond_out"]),
                make_node("Identity", ["v"], ["v_out"]),
                make_node("Identity", ["v"], ["scan0"]),
            ],
            ["iter_num", "cond_in", "v"], ["cond_out", "v_out", "scan0"],
            name="body",
        )
        raw = make_model(
            [make_node("Loop", ["M", "cond0", "x"], ["y", "ys"], body=body)],
            [("x", (2,))], ["y", "ys"],
            initializers={"M": np.int64(2), "cond0": np.bool_(True)},
        )
        with pytest.raises(Exception, match="scan_outputs"):
            import_onnx(raw)

    def test_if_passthrough_branch_captures_outer_tensor(self):
        """A zero-node branch returning an outer tensor directly (r4
        review finding)."""
        import numpy as np

        from onnx_fixtures import make_graph, make_model, make_node

        then_g = make_graph(
            [make_node("Mul", ["x", "two"], ["tout"])],
            [], ["tout"], initializers={"two": np.float32(2.0)},
            name="then",
        )
        else_g = make_graph([], [], ["x"], name="else")   # passthrough
        raw = make_model(
            [
                make_node("ReduceSum", ["x"], ["s"], keepdims=0),
                make_node("Constant", [], ["zero"], value=np.float32(0.0)),
                make_node("Greater", ["s", "zero"], ["pred"]),
                make_node("If", ["pred"], ["y"], then_branch=then_g,
                          else_branch=else_g),
            ],
            [("x", (3,))], ["y"],
        )
        sd = import_onnx(raw)
        xp = np.array([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": xp}, "y")), xp * 2.0, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": -xp}, "y")), -xp, atol=1e-6)

    def test_scan_cumulative_state_and_stacked_outputs(self):
        """Scan -> lax.scan: running sum state + per-step scan output."""
        import numpy as np

        from onnx_fixtures import make_graph, make_model, make_node

        # body: (acc, x_t) -> (acc + x_t, acc + x_t)   [state, scan_out]
        body = make_graph(
            [make_node("Add", ["acc", "x_t"], ["acc_out"]),
             make_node("Identity", ["acc_out"], ["y_t"])],
            ["acc", "x_t"], ["acc_out", "y_t"], name="body",
        )
        raw = make_model(
            [make_node("Scan", ["acc0", "xs"], ["acc_final", "ys"],
                       body=body, num_scan_inputs=1)],
            [("acc0", (2,)), ("xs", (5, 2))], ["acc_final", "ys"],
        )
        sd = import_onnx(raw)
        a0 = np.zeros(2, np.float32)
        xs = np.arange(10, dtype=np.float32).reshape(5, 2)
        want = np.cumsum(xs, axis=0)
        np.testing.assert_allclose(
            np.asarray(sd.output({"acc0": a0, "xs": xs}, "ys")), want,
            atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(sd.output({"acc0": a0, "xs": xs}, "acc_final")),
            want[-1], atol=1e-6)

    def test_scan_reverse_direction(self):
        import numpy as np

        from onnx_fixtures import make_graph, make_model, make_node

        body = make_graph(
            [make_node("Add", ["acc", "x_t"], ["acc_out"]),
             make_node("Identity", ["acc_out"], ["y_t"])],
            ["acc", "x_t"], ["acc_out", "y_t"], name="body",
        )
        raw = make_model(
            [make_node("Scan", ["acc0", "xs"], ["acc_final", "ys"],
                       body=body, num_scan_inputs=1,
                       scan_input_directions=[1],
                       scan_output_directions=[1])],
            [("acc0", (3,)), ("xs", (4, 3))], ["acc_final", "ys"],
        )
        sd = import_onnx(raw)
        a0 = np.zeros(3, np.float32)
        xs = np.arange(12, dtype=np.float32).reshape(4, 3)
        # reverse input + reverse output = suffix sums aligned to input
        want = np.cumsum(xs[::-1], axis=0)[::-1]
        np.testing.assert_allclose(
            np.asarray(sd.output({"acc0": a0, "xs": xs}, "ys")), want,
            atol=1e-6)


class TestOnnxRecurrentOps:
    """Fused ONNX LSTM/GRU/RNN nodes -> one lax.scan per direction;
    goldens computed with torch's reference cells."""

    def _run(self, raw, feeds, *fetches):
        sd = import_onnx(raw)
        return [np.asarray(sd.output(feeds, f)) for f in fetches]

    def test_lstm_forward_matches_torch(self):
        import torch

        from onnx_fixtures import make_model, make_node

        T, B, I, H = 6, 3, 4, 5
        torch.manual_seed(0)
        m = torch.nn.LSTM(I, H)
        x = torch.randn(T, B, I)
        want_y, (want_h, want_c) = m(x)

        # torch packs rows [i, f, g, o]; ONNX wants [i, o, f, c]
        def pack(w):
            i, f, g, o = np.split(w.detach().numpy(), 4, axis=0)
            return np.concatenate([i, o, f, g], axis=0)[None]

        W = pack(m.weight_ih_l0)
        R = pack(m.weight_hh_l0)
        bi, bh = (pack(b[:, None])[..., 0] for b in
                  (m.bias_ih_l0, m.bias_hh_l0))
        Bv = np.concatenate([bi, bh], axis=1)
        raw = make_model(
            [make_node("LSTM", ["x", "W", "R", "B"], ["Y", "Y_h", "Y_c"],
                       hidden_size=H)],
            [("x", (T, B, I))], ["Y", "Y_h", "Y_c"],
            initializers={"W": W.astype(np.float32),
                          "R": R.astype(np.float32),
                          "B": Bv.astype(np.float32)},
        )
        y, yh, yc = self._run(raw, {"x": x.numpy()}, "Y", "Y_h", "Y_c")
        np.testing.assert_allclose(y[:, 0], want_y.detach().numpy(),
                                   atol=2e-5, rtol=1e-4)
        np.testing.assert_allclose(yh, want_h.detach().numpy(),
                                   atol=2e-5, rtol=1e-4)
        np.testing.assert_allclose(yc, want_c.detach().numpy(),
                                   atol=2e-5, rtol=1e-4)

    def test_lstm_layout_batch_first_matches_time_major(self):
        """opset>=14 layout=1 (batch-first X/Y/states) must produce the
        transposed results of the identical layout=0 model — round 4
        imported layout=1 silently with swapped axes."""
        T, B, I, H = 5, 3, 4, 6
        W = RNG.normal(0, 0.4, (1, 4 * H, I)).astype(np.float32)
        R = RNG.normal(0, 0.4, (1, 4 * H, H)).astype(np.float32)
        Bv = RNG.normal(0, 0.1, (1, 8 * H)).astype(np.float32)
        x = RNG.normal(0, 1, (T, B, I)).astype(np.float32)
        h0 = RNG.normal(0, 1, (1, B, H)).astype(np.float32)
        c0 = RNG.normal(0, 1, (1, B, H)).astype(np.float32)
        inits = {"W": W, "R": R, "B": Bv}

        raw0 = make_model(
            [make_node("LSTM", ["x", "W", "R", "B", "", "h0", "c0"],
                       ["Y", "Y_h", "Y_c"], hidden_size=H)],
            [("x", (T, B, I)), ("h0", (1, B, H)), ("c0", (1, B, H))],
            ["Y", "Y_h", "Y_c"], initializers=inits)
        raw1 = make_model(
            [make_node("LSTM", ["x", "W", "R", "B", "", "h0", "c0"],
                       ["Y", "Y_h", "Y_c"], hidden_size=H, layout=1)],
            [("x", (B, T, I)), ("h0", (B, 1, H)), ("c0", (B, 1, H))],
            ["Y", "Y_h", "Y_c"], initializers=inits)

        y0, yh0, yc0 = self._run(
            raw0, {"x": x, "h0": h0, "c0": c0}, "Y", "Y_h", "Y_c")
        y1, yh1, yc1 = self._run(
            raw1,
            {"x": x.transpose(1, 0, 2), "h0": h0.transpose(1, 0, 2),
             "c0": c0.transpose(1, 0, 2)},
            "Y", "Y_h", "Y_c")
        np.testing.assert_allclose(y1, y0.transpose(2, 0, 1, 3), atol=1e-6)
        np.testing.assert_allclose(yh1, yh0.transpose(1, 0, 2), atol=1e-6)
        np.testing.assert_allclose(yc1, yc0.transpose(1, 0, 2), atol=1e-6)

    def test_gru_layout_rejected_when_invalid(self):
        T, B, I, H = 3, 2, 3, 4
        W = RNG.normal(0, 0.4, (1, 3 * H, I)).astype(np.float32)
        R = RNG.normal(0, 0.4, (1, 3 * H, H)).astype(np.float32)
        raw = make_model(
            [make_node("GRU", ["x", "W", "R"], ["Y"], hidden_size=H,
                       layout=2, linear_before_reset=1)],
            [("x", (T, B, I))], ["Y"],
            initializers={"W": W, "R": R})
        with pytest.raises(ONNXImportError, match="layout"):
            import_onnx(raw)

    def test_gru_linear_before_reset_matches_torch(self):
        import torch

        from onnx_fixtures import make_model, make_node

        T, B, I, H = 5, 2, 3, 4
        torch.manual_seed(1)
        m = torch.nn.GRU(I, H)
        x = torch.randn(T, B, I)
        want_y, want_h = m(x)

        # torch rows [r, z, n] -> ONNX [z, r, h]
        def pack(w):
            r, z, n = np.split(w.detach().numpy(), 3, axis=0)
            return np.concatenate([z, r, n], axis=0)[None]

        W = pack(m.weight_ih_l0)
        R = pack(m.weight_hh_l0)
        bi, bh = (pack(b[:, None])[..., 0] for b in
                  (m.bias_ih_l0, m.bias_hh_l0))
        Bv = np.concatenate([bi, bh], axis=1)
        raw = make_model(
            [make_node("GRU", ["x", "W", "R", "B"], ["Y", "Y_h"],
                       hidden_size=H, linear_before_reset=1)],
            [("x", (T, B, I))], ["Y", "Y_h"],
            initializers={"W": W.astype(np.float32),
                          "R": R.astype(np.float32),
                          "B": Bv.astype(np.float32)},
        )
        y, yh = self._run(raw, {"x": x.numpy()}, "Y", "Y_h")
        np.testing.assert_allclose(y[:, 0], want_y.detach().numpy(),
                                   atol=2e-5, rtol=1e-4)
        np.testing.assert_allclose(yh, want_h.detach().numpy(),
                                   atol=2e-5, rtol=1e-4)

    def test_bidirectional_rnn_matches_torch(self):
        import torch

        from onnx_fixtures import make_model, make_node

        T, B, I, H = 4, 2, 3, 3
        torch.manual_seed(2)
        m = torch.nn.RNN(I, H, bidirectional=True)
        x = torch.randn(T, B, I)
        want_y, want_h = m(x)   # (T, B, 2H), (2, B, H)

        def one(w):
            return w.detach().numpy()[None]

        W = np.concatenate([one(m.weight_ih_l0),
                            one(m.weight_ih_l0_reverse)], axis=0)
        R = np.concatenate([one(m.weight_hh_l0),
                            one(m.weight_hh_l0_reverse)], axis=0)
        Bv = np.stack([
            np.concatenate([m.bias_ih_l0.detach().numpy(),
                            m.bias_hh_l0.detach().numpy()]),
            np.concatenate([m.bias_ih_l0_reverse.detach().numpy(),
                            m.bias_hh_l0_reverse.detach().numpy()]),
        ])
        raw = make_model(
            [make_node("RNN", ["x", "W", "R", "B"], ["Y", "Y_h"],
                       hidden_size=H, direction="bidirectional")],
            [("x", (T, B, I))], ["Y", "Y_h"],
            initializers={"W": W.astype(np.float32),
                          "R": R.astype(np.float32),
                          "B": Bv.astype(np.float32)},
        )
        y, yh = self._run(raw, {"x": x.numpy()}, "Y", "Y_h")
        got = np.concatenate([y[:, 0], y[:, 1]], axis=-1)
        np.testing.assert_allclose(got, want_y.detach().numpy(),
                                   atol=2e-5, rtol=1e-4)
        np.testing.assert_allclose(yh, want_h.detach().numpy(),
                                   atol=2e-5, rtol=1e-4)

    def test_gru_reset_before_rejected(self):
        from onnx_fixtures import make_model, make_node

        raw = make_model(
            [make_node("GRU", ["x", "W", "R"], ["Y"], hidden_size=2)],
            [("x", (3, 1, 2))], ["Y"],
            initializers={"W": np.zeros((1, 6, 2), np.float32),
                          "R": np.zeros((1, 6, 2), np.float32)},
        )
        with pytest.raises(ONNXImportError, match="linear_before_reset"):
            import_onnx(raw)


class TestOnnxSourceBackedSerde:
    def test_loop_model_roundtrips_through_zip(self, tmp_path):
        import numpy as np

        from onnx_fixtures import make_graph, make_model, make_node
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        body = make_graph(
            [make_node("Mul", ["v", "two"], ["v2"]),
             make_node("Add", ["v2", "one"], ["v_out"]),
             make_node("Identity", ["cond_in"], ["cond_out"])],
            ["iter_num", "cond_in", "v"], ["cond_out", "v_out"],
            initializers={"two": np.float32(2.0), "one": np.float32(1.0)},
            name="b")
        raw = make_model(
            [make_node("Loop", ["M", "cond0", "x"], ["y"], body=body)],
            [("x", (3,))], ["y"],
            initializers={"M": np.int64(3), "cond0": np.bool_(True)})
        sd = import_onnx(raw)
        xv = np.array([1.0, 0.0, -1.0], np.float32)
        want = np.asarray(sd.output({"x": xv}, "y"))
        p = str(tmp_path / "loop.sd.zip")
        sd.save(p)
        sd2 = SameDiff.load(p)
        np.testing.assert_allclose(
            np.asarray(sd2.output({"x": xv}, "y")), want, atol=1e-6)

    def test_initial_states_respect_empty_slots(self):
        """initial_c WITHOUT initial_h: the empty slot must not shift
        (r4 review finding — c0 was silently used as h0)."""
        import torch

        from onnx_fixtures import make_model, make_node

        T, B, I, H = 4, 2, 3, 4
        torch.manual_seed(3)
        m = torch.nn.LSTM(I, H)
        x = torch.randn(T, B, I)
        c0 = torch.randn(1, B, H)
        h0 = torch.zeros(1, B, H)
        want_y, _ = m(x, (h0, c0))

        def pack(w):
            i, f, g, o = np.split(w.detach().numpy(), 4, axis=0)
            return np.concatenate([i, o, f, g], axis=0)[None]

        W, R = pack(m.weight_ih_l0), pack(m.weight_hh_l0)
        bi, bh = (pack(b[:, None])[..., 0] for b in
                  (m.bias_ih_l0, m.bias_hh_l0))
        raw = make_model(
            [make_node("LSTM", ["x", "W", "R", "B", "", "", "c0"], ["Y"],
                       hidden_size=H)],
            [("x", (T, B, I)), ("c0", (1, B, H))], ["Y"],
            initializers={"W": W.astype(np.float32),
                          "R": R.astype(np.float32),
                          "B": np.concatenate([bi, bh], 1).astype(np.float32)},
        )
        sd = import_onnx(raw)
        y = np.asarray(sd.output({"x": x.numpy(), "c0": c0.numpy()}, "Y"))
        np.testing.assert_allclose(y[:, 0], want_y.detach().numpy(),
                                   atol=2e-5, rtol=1e-4)

    def test_peephole_and_clip_rejected(self):
        from onnx_fixtures import make_model, make_node

        raw = make_model(
            [make_node("LSTM", ["x", "W", "R", "B", "", "", "", "P"],
                       ["Y"], hidden_size=2)],
            [("x", (3, 1, 2))], ["Y"],
            initializers={"W": np.zeros((1, 8, 2), np.float32),
                          "R": np.zeros((1, 8, 2), np.float32),
                          "B": np.zeros((1, 16), np.float32),
                          "P": np.zeros((1, 6), np.float32)},
        )
        with pytest.raises(ONNXImportError, match="peephole"):
            import_onnx(raw)
        raw2 = make_model(
            [make_node("LSTM", ["x", "W", "R"], ["Y"], hidden_size=2,
                       clip=3.0)],
            [("x", (3, 1, 2))], ["Y"],
            initializers={"W": np.zeros((1, 8, 2), np.float32),
                          "R": np.zeros((1, 8, 2), np.float32)},
        )
        with pytest.raises(ONNXImportError, match="clip"):
            import_onnx(raw2)

    def test_set_value_survives_source_backed_serde(self, tmp_path):
        """Runtime-mutated imported constants must persist through the
        source-backed zip (r4 review finding)."""
        import numpy as np

        from onnx_fixtures import make_graph, make_model, make_node
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        body = make_graph(
            [make_node("Add", ["v", "one"], ["v_out"]),
             make_node("Identity", ["cond_in"], ["cond_out"])],
            ["iter_num", "cond_in", "v"], ["cond_out", "v_out"],
            initializers={"one": np.float32(1.0)}, name="b")
        raw = make_model(
            [make_node("Loop", ["M", "cond0", "x"], ["l"], body=body),
             make_node("Mul", ["l", "k"], ["y"])],
            [("x", (2,))], ["y"],
            initializers={"M": np.int64(2), "cond0": np.bool_(True),
                          "k": np.array([2.0, 3.0], np.float32)})
        sd = import_onnx(raw)
        # k is a top-level imported const consumed as a tensor; mutate it
        # at runtime — the source-backed zip must carry the NEW value
        sd.set_value("k", np.array([5.0, 10.0], np.float32))
        xv = np.array([1.0, 1.0], np.float32)
        want = np.asarray(sd.output({"x": xv}, "y"))
        np.testing.assert_allclose(want, [15.0, 30.0], atol=1e-5)
        p = str(tmp_path / "mut.sd.zip")
        sd.save(p)
        sd2 = SameDiff.load(p)
        np.testing.assert_allclose(
            np.asarray(sd2.output({"x": xv}, "y")), want, atol=1e-5)

    def test_double_roundtrip_keeps_mutation(self, tmp_path):
        """save -> load -> save -> load must not revert set_value
        mutations (r4 review finding)."""
        import numpy as np

        from onnx_fixtures import make_graph, make_model, make_node
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        body = make_graph(
            [make_node("Add", ["v", "one"], ["v_out"]),
             make_node("Identity", ["cond_in"], ["cond_out"])],
            ["iter_num", "cond_in", "v"], ["cond_out", "v_out"],
            initializers={"one": np.float32(1.0)}, name="b")
        raw = make_model(
            [make_node("Loop", ["M", "cond0", "x"], ["l"], body=body),
             make_node("Mul", ["l", "k"], ["y"])],
            [("x", (2,))], ["y"],
            initializers={"M": np.int64(1), "cond0": np.bool_(True),
                          "k": np.array([2.0, 2.0], np.float32)})
        sd = import_onnx(raw)
        sd.set_value("k", np.array([10.0, 10.0], np.float32))
        xv = np.zeros(2, np.float32)
        want = np.asarray(sd.output({"x": xv}, "y"))
        p1, p2 = str(tmp_path / "a.zip"), str(tmp_path / "b.zip")
        sd.save(p1)
        sd2 = SameDiff.load(p1)
        sd2.save(p2)
        sd3 = SameDiff.load(p2)
        np.testing.assert_allclose(
            np.asarray(sd3.output({"x": xv}, "y")), want, atol=1e-6)
