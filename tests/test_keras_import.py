"""Keras import golden tests.

The reference validates Keras import against Keras-produced golden HDF5
files (SURVEY.md §4.1 "Keras import tests").  tensorflow is available in
this environment, so the goldens are produced live: build a tf.keras model,
save legacy HDF5, import, and assert prediction equality on random inputs.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.modelimport.keras import (  # noqa: E402
    KerasImportError,
    KerasModelImport,
    import_keras_model,
)

keras = tf.keras


def save_h5(model, tmp_path, name="m.h5"):
    p = str(tmp_path / name)
    model.save(p)
    return p


def assert_outputs_match(kmodel, ours, x, atol=1e-4):
    want = np.asarray(kmodel(x, training=False))
    got = ours.output(x.astype(np.float32))
    if isinstance(got, tuple):          # GraphModel returns one per output
        (got,) = got
    got = np.asarray(got)
    np.testing.assert_allclose(got, want, atol=atol, rtol=1e-3)


class TestSequentialImport:
    def test_mlp_softmax(self, tmp_path):
        km = keras.Sequential(
            [
                keras.layers.Input((8,)),
                keras.layers.Dense(16, activation="relu"),
                keras.layers.Dense(3, activation="softmax"),
            ]
        )
        km.compile(loss="categorical_crossentropy", optimizer="adam")
        ours = import_keras_model(save_h5(km, tmp_path))
        x = np.random.default_rng(0).normal(size=(5, 8)).astype(np.float32)
        assert_outputs_match(km, ours, x)
        # loss came through from training_config
        from deeplearning4j_tpu.nn.losses import Loss

        assert ours.conf.layers[-1].loss == Loss.MCXENT

    def test_cnn_with_bn_pool_dropout(self, tmp_path):
        km = keras.Sequential(
            [
                keras.layers.Input((12, 12, 3)),
                keras.layers.Conv2D(8, 3, activation="relu", padding="same"),
                keras.layers.BatchNormalization(),
                keras.layers.MaxPooling2D(2),
                keras.layers.Conv2D(4, 3, padding="valid", use_bias=False),
                keras.layers.Activation("tanh"),
                keras.layers.Flatten(),
                keras.layers.Dropout(0.25),
                keras.layers.Dense(2, activation="sigmoid"),
            ]
        )
        # perturb BN running stats so inference actually uses them
        bn = km.layers[1]
        bn.moving_mean.assign(np.random.default_rng(1).normal(0, 0.3, bn.moving_mean.shape))
        bn.moving_variance.assign(np.abs(np.random.default_rng(2).normal(1, 0.2, bn.moving_variance.shape)))
        ours = import_keras_model(save_h5(km, tmp_path))
        x = np.random.default_rng(3).normal(size=(4, 12, 12, 3)).astype(np.float32)
        assert_outputs_match(km, ours, x)

    def test_global_avg_pool(self, tmp_path):
        km = keras.Sequential(
            [
                keras.layers.Input((8, 8, 4)),
                keras.layers.Conv2D(6, 3),
                keras.layers.GlobalAveragePooling2D(),
                keras.layers.Dense(2),
            ]
        )
        ours = import_keras_model(save_h5(km, tmp_path))
        x = np.random.default_rng(4).normal(size=(3, 8, 8, 4)).astype(np.float32)
        assert_outputs_match(km, ours, x)

    def test_lstm_sequence_model(self, tmp_path):
        km = keras.Sequential(
            [
                keras.layers.Input((6, 5)),
                keras.layers.LSTM(7, return_sequences=False),
                keras.layers.Dense(2, activation="softmax"),
            ]
        )
        ours_path = save_h5(km, tmp_path)
        try:
            ours = import_keras_model(ours_path)
        except KerasImportError as e:
            pytest.skip(f"LSTM dialect unsupported: {e}")
        x = np.random.default_rng(5).normal(size=(3, 6, 5)).astype(np.float32)
        want = np.asarray(km(x, training=False))
        got = np.asarray(ours.output(x))
        # keras LSTM returns last step; our recurrent stack may return sequences
        if got.ndim == 3:
            got = got[:, -1, :]
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)

    def test_embedding_model(self, tmp_path):
        km = keras.Sequential(
            [
                keras.layers.Input((4,), dtype="int32"),
                keras.layers.Embedding(11, 6),
                keras.layers.GlobalAveragePooling1D(),
                keras.layers.Dense(2),
            ]
        )
        try:
            ours = import_keras_model(save_h5(km, tmp_path))
        except KerasImportError as e:
            pytest.skip(f"dialect gap: {e}")
        x = np.random.default_rng(6).integers(0, 11, size=(3, 4)).astype(np.int32)
        want = np.asarray(km(x, training=False))
        got = np.asarray(ours.output(x))
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)


class TestFunctionalImport:
    def test_linear_functional_chain(self, tmp_path):
        inp = keras.layers.Input((10,))
        h = keras.layers.Dense(8, activation="relu")(inp)
        out = keras.layers.Dense(2, activation="softmax")(h)
        km = keras.Model(inp, out)
        ours = KerasModelImport.import_keras_model_and_weights(save_h5(km, tmp_path))
        x = np.random.default_rng(7).normal(size=(4, 10)).astype(np.float32)
        assert_outputs_match(km, ours, x)

    def test_branching_rejected_by_sequential_entry(self, tmp_path):
        inp = keras.layers.Input((6,))
        a = keras.layers.Dense(4)(inp)
        b = keras.layers.Dense(4)(inp)
        out = keras.layers.Add()([a, b])
        km = keras.Model(inp, out)
        with pytest.raises(KerasImportError, match="[Bb]ranching|Add"):
            import_keras_model(save_h5(km, tmp_path))


class TestBranchingFunctionalImport:
    """Branching graphs -> GraphModel (the ComputationGraph-returning
    reference entry, now real)."""

    def test_residual_add_branch(self, tmp_path):
        from deeplearning4j_tpu.modelimport.keras import import_keras_graph

        inp = keras.layers.Input((12,))
        h = keras.layers.Dense(12, activation="tanh")(inp)
        res = keras.layers.Add()([inp, h])
        out = keras.layers.Dense(3, activation="softmax")(res)
        km = keras.Model(inp, out)
        km.compile(loss="categorical_crossentropy", optimizer="adam")
        ours = import_keras_graph(save_h5(km, tmp_path))
        x = np.random.default_rng(1).normal(size=(6, 12)).astype(np.float32)
        assert_outputs_match(km, ours, x)

    def test_two_branch_concat_cnn(self, tmp_path):
        from deeplearning4j_tpu.modelimport.keras import import_keras_graph

        inp = keras.layers.Input((8, 8, 3))
        a = keras.layers.Conv2D(4, 3, padding="same", activation="relu")(inp)
        b = keras.layers.Conv2D(4, 1, padding="same")(inp)
        m = keras.layers.Concatenate()([a, b])
        p = keras.layers.GlobalAveragePooling2D()(m)
        out = keras.layers.Dense(2, activation="softmax")(p)
        km = keras.Model(inp, out)
        ours = import_keras_graph(save_h5(km, tmp_path))
        x = np.random.default_rng(2).normal(size=(3, 8, 8, 3)).astype(np.float32)
        assert_outputs_match(km, ours, x)

    def test_multi_input_model(self, tmp_path):
        from deeplearning4j_tpu.modelimport.keras import import_keras_graph

        in1 = keras.layers.Input((5,))
        in2 = keras.layers.Input((7,))
        h1 = keras.layers.Dense(6, activation="relu")(in1)
        h2 = keras.layers.Dense(6, activation="relu")(in2)
        m = keras.layers.Concatenate()([h1, h2])
        out = keras.layers.Dense(2)(m)
        km = keras.Model([in1, in2], out)
        ours = import_keras_graph(save_h5(km, tmp_path))
        rng = np.random.default_rng(3)
        x1 = rng.normal(size=(4, 5)).astype(np.float32)
        x2 = rng.normal(size=(4, 7)).astype(np.float32)
        want = np.asarray(km([x1, x2], training=False))
        got = ours.output(x1, x2)
        if isinstance(got, tuple):
            (got,) = got
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-3)

    def test_reversed_declared_input_order(self, tmp_path):
        """Model([in2, in1], ...) serializes layers in creation order but
        input_layers in declared order — types must follow the latter."""
        from deeplearning4j_tpu.modelimport.keras import import_keras_graph

        in1 = keras.layers.Input((5,))
        in2 = keras.layers.Input((7,))
        h1 = keras.layers.Dense(4)(in1)
        h2 = keras.layers.Dense(4)(in2)
        m = keras.layers.Concatenate()([h1, h2])
        out = keras.layers.Dense(2)(m)
        km = keras.Model([in2, in1], out)       # reversed declaration
        ours = import_keras_graph(save_h5(km, tmp_path))
        rng = np.random.default_rng(5)
        x2 = rng.normal(size=(3, 7)).astype(np.float32)
        x1 = rng.normal(size=(3, 5)).astype(np.float32)
        want = np.asarray(km([x2, x1], training=False))
        got = ours.output(x2, x1)
        if isinstance(got, tuple):
            (got,) = got
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-3)

    def test_facade_dispatches_both_kinds(self, tmp_path):
        seq = keras.Sequential([
            keras.layers.Input((4,)),
            keras.layers.Dense(2, activation="softmax"),
        ])
        ours_seq = KerasModelImport.import_keras_model_and_weights(
            save_h5(seq, tmp_path, "seq.h5")
        )
        from deeplearning4j_tpu.models import SequentialModel
        from deeplearning4j_tpu.models.computation_graph import GraphModel

        assert isinstance(ours_seq, SequentialModel)
        inp = keras.layers.Input((4,))
        out = keras.layers.Add()([keras.layers.Dense(4)(inp),
                                  keras.layers.Dense(4)(inp)])
        km = keras.Model(inp, out)
        ours_g = KerasModelImport.import_keras_model_and_weights(
            save_h5(km, tmp_path, "fun.h5")
        )
        assert isinstance(ours_g, GraphModel)

    def test_concatenate_explicit_trailing_axis(self, tmp_path):
        from deeplearning4j_tpu.modelimport.keras import import_keras_graph

        inp = keras.layers.Input((8, 8, 3))
        a = keras.layers.Conv2D(2, 1)(inp)
        b2 = keras.layers.Conv2D(2, 1)(inp)
        m = keras.layers.Concatenate(axis=3)([a, b2])    # == axis=-1 on NHWC
        p = keras.layers.GlobalAveragePooling2D()(m)
        out = keras.layers.Dense(2)(p)
        km = keras.Model(inp, out)
        ours = import_keras_graph(save_h5(km, tmp_path))
        x = np.random.default_rng(8).normal(size=(2, 8, 8, 3)).astype(np.float32)
        assert_outputs_match(km, ours, x)

    def test_concatenate_non_trailing_axis_rejected(self, tmp_path):
        from deeplearning4j_tpu.modelimport.keras import import_keras_graph

        inp = keras.layers.Input((8, 8, 3))
        a = keras.layers.Conv2D(2, 1)(inp)
        b2 = keras.layers.Conv2D(2, 1)(inp)
        m = keras.layers.Concatenate(axis=1)([a, b2])    # height concat
        out = keras.layers.Dense(2)(keras.layers.Flatten()(m))
        km = keras.Model(inp, out)
        with pytest.raises(ValueError, match="trailing axis"):
            import_keras_graph(save_h5(km, tmp_path))

    def test_multi_output_losses_keyed_by_name(self, tmp_path):
        from deeplearning4j_tpu.modelimport.keras import import_keras_graph
        from deeplearning4j_tpu.nn.losses import Loss

        inp = keras.layers.Input((6,))
        h = keras.layers.Dense(8, activation="relu")(inp)
        out_a = keras.layers.Dense(1, name="reg_head")(h)
        out_b = keras.layers.Dense(3, activation="softmax", name="cls_head")(h)
        km = keras.Model(inp, [out_a, out_b])
        km.compile(optimizer="adam",
                   loss={"reg_head": "mse",
                         "cls_head": "categorical_crossentropy"})
        ours = import_keras_graph(save_h5(km, tmp_path))
        by_name = {n.name: n for n in ours.conf.nodes}
        assert by_name["reg_head"].layer.loss == Loss.MSE
        assert by_name["cls_head"].layer.loss == Loss.MCXENT

    def test_imported_graph_trains(self, tmp_path):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.modelimport.keras import import_keras_graph

        inp = keras.layers.Input((6,))
        h = keras.layers.Dense(8, activation="tanh")(inp)
        res = keras.layers.Add()([h, keras.layers.Dense(8)(inp)])
        out = keras.layers.Dense(3, activation="softmax")(res)
        km = keras.Model(inp, out)
        km.compile(loss="categorical_crossentropy", optimizer="adam")
        ours = import_keras_graph(save_h5(km, tmp_path))
        rng = np.random.default_rng(4)
        x = rng.normal(size=(32, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
        first = None
        for _ in range(20):
            ours.fit_batch(DataSet(x, y))
            first = first if first is not None else ours.score_value
        assert ours.score_value < first


class TestReviewRegressions:
    def test_variable_length_sequence_input(self, tmp_path):
        km = keras.Sequential(
            [
                keras.layers.Input((None, 5)),
                keras.layers.LSTM(4),
                keras.layers.Dense(2, activation="softmax"),
            ]
        )
        ours = import_keras_model(save_h5(km, tmp_path))
        x = np.random.default_rng(8).normal(size=(2, 7, 5)).astype(np.float32)
        want = np.asarray(km(x, training=False))
        got = np.asarray(ours.output(x))
        if got.ndim == 3:
            got = got[:, -1, :]
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)

    def test_trailing_activation_layer_folds_into_output(self, tmp_path):
        km = keras.Sequential(
            [
                keras.layers.Input((8,)),
                keras.layers.Dense(3),
                keras.layers.Activation("softmax"),
            ]
        )
        ours = import_keras_model(save_h5(km, tmp_path))
        x = np.random.default_rng(9).normal(size=(4, 8)).astype(np.float32)
        assert_outputs_match(km, ours, x)

    def test_non_dense_tail_gets_loss_layer(self, tmp_path):
        km = keras.Sequential(
            [
                keras.layers.Input((8, 8, 2)),
                keras.layers.Conv2D(3, 3),
                keras.layers.GlobalAveragePooling2D(),
            ]
        )
        ours = import_keras_model(save_h5(km, tmp_path))
        x = np.random.default_rng(10).normal(size=(3, 8, 8, 2)).astype(np.float32)
        assert_outputs_match(km, ours, x)


class TestErrorPaths:
    def test_weights_only_file_rejected(self, tmp_path):
        km = keras.Sequential([keras.layers.Input((4,)), keras.layers.Dense(2)])
        p = str(tmp_path / "w.weights.h5")
        km.save_weights(p)
        with pytest.raises(KerasImportError, match="model_config"):
            import_keras_model(p)


class TestCustomLayerRegistry:
    def test_register_custom_layer_maps_and_imports(self, tmp_path):
        """A Keras Lambda-style custom class the importer doesn't know is
        taught via register_keras_layer (the reference's
        KerasLayer.registerCustomLayer role)."""
        from deeplearning4j_tpu.modelimport.keras import (
            register_keras_layer,
            registered_keras_layers,
        )
        from deeplearning4j_tpu.nn.activations import Activation
        from deeplearning4j_tpu.nn.conf import ActivationLayer

        @keras.utils.register_keras_serializable(package="test")
        class DoubleRelu(keras.layers.Layer):
            def call(self, x):
                return tf.nn.relu(x) * 2.0

        km = keras.Sequential(
            [
                keras.layers.Input((4,)),
                keras.layers.Dense(6, activation="linear"),
                DoubleRelu(),
                keras.layers.Dense(2, activation="softmax"),
            ]
        )
        km.compile(loss="categorical_crossentropy", optimizer="adam")
        path = save_h5(km, tmp_path)

        with pytest.raises(KerasImportError, match="register_keras_layer"):
            import_keras_model(path)

        import dataclasses
        from deeplearning4j_tpu.nn.conf.layers import LayerConfig
        from deeplearning4j_tpu.utils import serde
        import jax.numpy as jnp

        @serde.register
        @dataclasses.dataclass(frozen=True)
        class DoubleReluLayer(LayerConfig):
            HAS_PARAMS = False
            REGULARIZED = ()

            def apply(self, params, state, x, *, training=False, rng=None):
                return jnp.maximum(x, 0.0) * 2.0, state

        # keras serializes registered custom classes as "package>Class"
        register_keras_layer(
            "test>DoubleRelu", lambda cfg, name: DoubleReluLayer(name=name)
        )
        try:
            assert "test>DoubleRelu" in registered_keras_layers()
            ours = import_keras_model(path)
            x = np.random.default_rng(1).normal(size=(5, 4)).astype(np.float32)
            assert_outputs_match(km, ours, x)
        finally:
            from deeplearning4j_tpu.modelimport.keras import _LAYER_MAPPERS

            _LAYER_MAPPERS.pop("test>DoubleRelu", None)

    def test_register_rejects_non_callable(self):
        from deeplearning4j_tpu.modelimport.keras import register_keras_layer

        with pytest.raises(TypeError):
            register_keras_layer("X", "not-a-function")


class TestRound3LayerBreadth:
    def test_conv1d_stack(self, tmp_path):
        km = keras.Sequential([
            keras.layers.Input((12, 5)),
            keras.layers.Conv1D(8, 3, padding="same", activation="relu"),
            keras.layers.Conv1D(6, 3, strides=2, padding="valid"),
            keras.layers.GlobalAveragePooling1D(),
            keras.layers.Dense(3),
        ])
        ours = import_keras_model(save_h5(km, tmp_path))
        x = np.random.default_rng(0).normal(size=(4, 12, 5)).astype(np.float32)
        assert_outputs_match(km, ours, x)

    def test_separable_conv(self, tmp_path):
        km = keras.Sequential([
            keras.layers.Input((10, 10, 3)),
            keras.layers.SeparableConv2D(8, 3, padding="same",
                                         depth_multiplier=2,
                                         activation="relu"),
            keras.layers.GlobalAveragePooling2D(),
            keras.layers.Dense(2),
        ])
        ours = import_keras_model(save_h5(km, tmp_path))
        x = np.random.default_rng(1).normal(size=(2, 10, 10, 3)).astype(np.float32)
        assert_outputs_match(km, ours, x)

    def test_gru_reset_after(self, tmp_path):
        km = keras.Sequential([
            keras.layers.Input((7, 4)),
            keras.layers.GRU(6, return_sequences=True),
            keras.layers.GRU(5),
            keras.layers.Dense(2, activation="sigmoid"),
        ])
        ours = import_keras_model(save_h5(km, tmp_path))
        x = np.random.default_rng(2).normal(size=(3, 7, 4)).astype(np.float32)
        assert_outputs_match(km, ours, x)

    def test_layernorm_prelu_activations(self, tmp_path):
        km = keras.Sequential([
            keras.layers.Input((9,)),
            keras.layers.Dense(12),
            keras.layers.LayerNormalization(),
            keras.layers.PReLU(),
            keras.layers.Dense(8),
            keras.layers.LeakyReLU(),
            keras.layers.Dense(4),
            keras.layers.ELU(),
            keras.layers.Dense(2),
        ])
        ours = import_keras_model(save_h5(km, tmp_path))
        x = np.random.default_rng(3).normal(size=(5, 9)).astype(np.float32)
        assert_outputs_match(km, ours, x)

    def test_upsampling_cropping(self, tmp_path):
        km = keras.Sequential([
            keras.layers.Input((6, 6, 2)),
            keras.layers.UpSampling2D(2),
            keras.layers.Cropping2D(((1, 1), (2, 2))),
            keras.layers.Conv2D(3, 3, padding="same"),
            keras.layers.GlobalMaxPooling2D(),
        ])
        ours = import_keras_model(save_h5(km, tmp_path))
        x = np.random.default_rng(4).normal(size=(2, 6, 6, 2)).astype(np.float32)
        assert_outputs_match(km, ours, x)

    def test_gru_reset_after_false_rejected(self, tmp_path):
        km = keras.Sequential([
            keras.layers.Input((5, 3)),
            keras.layers.GRU(4, reset_after=False),
            keras.layers.Dense(2),
        ])
        with pytest.raises(KerasImportError, match="reset_after"):
            import_keras_model(save_h5(km, tmp_path))

    def test_conv2d_transpose(self, tmp_path):
        km = keras.Sequential([
            keras.layers.Input((5, 5, 3)),
            keras.layers.Conv2DTranspose(4, 3, strides=2, padding="same",
                                         activation="relu"),
            keras.layers.Conv2DTranspose(2, 2, strides=1, padding="valid"),
            keras.layers.GlobalAveragePooling2D(),
        ])
        ours = import_keras_model(save_h5(km, tmp_path))
        x = np.random.default_rng(5).normal(size=(2, 5, 5, 3)).astype(np.float32)
        assert_outputs_match(km, ours, x)

    def test_simplernn_and_1d_pools(self, tmp_path):
        km = keras.Sequential([
            keras.layers.Input((12, 4)),
            keras.layers.Conv1D(6, 3, padding="same", activation="relu"),
            keras.layers.MaxPooling1D(2),
            keras.layers.SimpleRNN(5, return_sequences=True),
            keras.layers.AveragePooling1D(2),
            keras.layers.SimpleRNN(4),
            keras.layers.Dense(2),
        ])
        ours = import_keras_model(save_h5(km, tmp_path))
        x = np.random.default_rng(6).normal(size=(3, 12, 4)).astype(np.float32)
        assert_outputs_match(km, ours, x)


class TestSharedLayerImport:
    """Shared-layer functional topology (a layer called on several
    inputs) imports with ONE param set via GraphNode.param_key."""

    def test_siamese_shared_encoder(self, tmp_path):
        keras = tf.keras
        rng = np.random.default_rng(0)
        enc = keras.layers.Dense(8, activation="relu", name="enc")
        in_a = keras.layers.Input((6,), name="ia")
        in_b = keras.layers.Input((6,), name="ib")
        ea, eb = enc(in_a), enc(in_b)
        merged = keras.layers.concatenate([ea, eb])
        out = keras.layers.Dense(3, name="head")(merged)
        m = keras.Model([in_a, in_b], out)
        p = str(tmp_path / "siamese.h5")
        m.save(p)

        from deeplearning4j_tpu.modelimport.keras import import_keras_graph

        gm = import_keras_graph(p)
        assert "enc" in gm.params and "enc__call1" not in gm.params
        xa = rng.normal(size=(4, 6)).astype(np.float32)
        xb = rng.normal(size=(4, 6)).astype(np.float32)
        want = np.asarray(m([xa, xb], training=False))
        got = np.asarray(gm.output(xa, xb))
        np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-3)

    def test_shared_lstm_chain(self, tmp_path):
        """Shared layer whose mapper emits a CHAIN (LSTM + LastTimeStep)."""
        keras = tf.keras
        rng = np.random.default_rng(1)
        enc = keras.layers.LSTM(5, name="lenc")
        a = keras.layers.Input((7, 4), name="xa")
        b = keras.layers.Input((7, 4), name="xb")
        d = keras.layers.subtract([enc(a), enc(b)])
        out = keras.layers.Dense(2, name="head")(d)
        m = keras.Model([a, b], out)
        p = str(tmp_path / "shared_lstm.h5")
        m.save(p)

        from deeplearning4j_tpu.modelimport.keras import import_keras_graph

        gm = import_keras_graph(p)
        assert "lenc" in gm.params
        xa = rng.normal(size=(3, 7, 4)).astype(np.float32)
        xb = rng.normal(size=(3, 7, 4)).astype(np.float32)
        want = np.asarray(m([xa, xb], training=False))
        got = np.asarray(gm.output(xa, xb))
        np.testing.assert_allclose(got, want, atol=3e-4, rtol=1e-3)
        # identical inputs through tied encoders cancel exactly
        same = np.asarray(gm.output(xa, xa))
        base = np.asarray(gm.output(xb, xb))
        np.testing.assert_allclose(same, base, atol=1e-5)

    def test_output_from_second_call(self, tmp_path):
        """A graph output produced by a NON-first call of a shared layer
        must wire to that call's vertex (r4 review finding)."""
        keras = tf.keras
        rng = np.random.default_rng(2)
        enc = keras.layers.Dense(4, name="enc2")
        a = keras.layers.Input((5,), name="pa")
        b2 = keras.layers.Input((5,), name="pb")
        ya = enc(a)
        yb = enc(b2)
        m = keras.Model([a, b2], [ya, yb])
        p = str(tmp_path / "two_out.h5")
        m.save(p)

        from deeplearning4j_tpu.modelimport.keras import import_keras_graph

        gm = import_keras_graph(p)
        xa = rng.normal(size=(3, 5)).astype(np.float32)
        xb = rng.normal(size=(3, 5)).astype(np.float32)
        wa, wb = (np.asarray(t) for t in m([xa, xb], training=False))
        got = gm.output(xa, xb)
        np.testing.assert_allclose(np.asarray(got[0]), wa, atol=2e-4,
                                   rtol=1e-3)
        # the second output must be enc(xb), NOT a rewire of enc(xa)
        np.testing.assert_allclose(np.asarray(got[1]), wb, atol=2e-4,
                                   rtol=1e-3)


class TestKeras3NativeFormat:
    """Keras-3 .keras zip archives (config.json + ordered-vars weights)
    convert to the legacy layout and ride the standard import path."""

    def _check(self, m, x, tmp_path, tag, atol=3e-4):
        from deeplearning4j_tpu.modelimport.keras import import_keras_auto

        p = str(tmp_path / f"{tag}.keras")
        m.save(p)
        want = np.asarray(m(x, training=False))
        got = np.asarray(import_keras_auto(p).output(x))
        np.testing.assert_allclose(got, want, atol=atol, rtol=1e-3)

    def test_mlp_cnn_rnn(self, tmp_path):
        keras = tf.keras
        rng = np.random.default_rng(0)
        m = keras.Sequential([
            keras.layers.Input((10, 10, 3)),
            keras.layers.Conv2D(6, 3, padding="same", activation="relu"),
            keras.layers.BatchNormalization(),
            keras.layers.MaxPooling2D(),
            keras.layers.Flatten(),
            keras.layers.Dense(4),
        ])
        self._check(m, rng.normal(size=(2, 10, 10, 3)).astype(np.float32),
                    tmp_path, "cnn")
        m = keras.Sequential([
            keras.layers.Input((7, 5)),
            keras.layers.LSTM(6, return_sequences=True),
            keras.layers.GRU(4),
            keras.layers.Dense(2),
        ])
        self._check(m, rng.normal(size=(3, 7, 5)).astype(np.float32),
                    tmp_path, "rnn")

    def test_optional_weights_dropped_mid_order(self, tmp_path):
        """BN scale=False / LN center=False shift the vars order from the
        FRONT/middle — names must come from the config, not a fixed
        prefix (r4 review finding)."""
        keras = tf.keras
        rng = np.random.default_rng(1)
        m = keras.Sequential([
            keras.layers.Input((8, 8, 2)),
            keras.layers.Conv2D(4, 3, padding="same", use_bias=False),
            keras.layers.BatchNormalization(scale=False),
            keras.layers.Flatten(),
            keras.layers.Dense(2),
        ])
        self._check(m, rng.normal(size=(2, 8, 8, 2)).astype(np.float32),
                    tmp_path, "bn_noscale")
        m = keras.Sequential([
            keras.layers.Input((6,)),
            keras.layers.Dense(8, activation="relu"),
            keras.layers.LayerNormalization(center=False),
            keras.layers.Dense(3),
        ])
        self._check(m, rng.normal(size=(4, 6)).astype(np.float32),
                    tmp_path, "ln_nocenter")

    def test_wrapper_layers(self, tmp_path):
        """Bidirectional/TimeDistributed weights nest under
        forward_layer/backward_layer/layer paths (r4 review finding)."""
        keras = tf.keras
        rng = np.random.default_rng(2)
        m = keras.Sequential([
            keras.layers.Input((6, 4)),
            keras.layers.Bidirectional(
                keras.layers.LSTM(5, return_sequences=True)),
            keras.layers.TimeDistributed(
                keras.layers.Dense(3, activation="relu")),
            keras.layers.Bidirectional(keras.layers.GRU(2)),
            keras.layers.Dense(2),
        ])
        self._check(m, rng.normal(size=(3, 6, 4)).astype(np.float32),
                    tmp_path, "wrappers")

    def test_functional_keras3(self, tmp_path):
        keras = tf.keras
        rng = np.random.default_rng(3)
        inp = keras.layers.Input((9,))
        a = keras.layers.Dense(8, activation="relu")(inp)
        b = keras.layers.Dense(8, activation="tanh")(inp)
        out = keras.layers.Dense(3)(keras.layers.concatenate([a, b]))
        m = keras.Model(inp, out)
        self._check(m, rng.normal(size=(4, 9)).astype(np.float32),
                    tmp_path, "functional")
