"""TF GraphDef import golden tests.

The reference's TF import regression suite runs thousands of tiny frozen
graphs against TensorFlow-produced golden outputs (SURVEY.md §4.1 "TF
import regression suite").  TensorFlow is available here, so goldens are
produced live: build a TF1-style graph of constants, take its GraphDef,
evaluate with a TF session, import into SameDiff, compare.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
tf1 = tf.compat.v1

from deeplearning4j_tpu.modelimport.tensorflow import (  # noqa: E402
    TFGraphMapper,
    TFImportError,
    import_graph,
    import_onnx,
)


def golden(graph, feeds, fetch):
    with tf1.Session(graph=graph) as sess:
        return sess.run(fetch, feeds)


def assert_graph_matches(build_fn, feeds, fetch_name, atol=1e-5):
    """build_fn constructs ops inside a fresh TF1 graph and returns nothing."""
    g = tf1.Graph()
    with g.as_default():
        build_fn()
    want = golden(g, {f"{k}:0": v for k, v in feeds.items()}, f"{fetch_name}:0")
    sd = import_graph(g.as_graph_def())
    got = sd.output(feeds, fetch_name)
    np.testing.assert_allclose(np.asarray(got), want, atol=atol, rtol=1e-4)
    return sd


class TestBasicGraphs:
    def test_mlp(self):
        rng = np.random.default_rng(0)
        w1, b1 = rng.normal(size=(4, 8)).astype(np.float32), rng.normal(size=(8,)).astype(np.float32)
        w2 = rng.normal(size=(8, 3)).astype(np.float32)

        def build():
            x = tf1.placeholder(tf.float32, [None, 4], name="x")
            h = tf.nn.relu(tf.nn.bias_add(tf.matmul(x, tf.constant(w1)), tf.constant(b1)))
            tf.nn.softmax(tf.matmul(h, tf.constant(w2)), name="out")

        assert_graph_matches(build, {"x": rng.normal(size=(5, 4)).astype(np.float32)}, "out")

    def test_conv_pool_net(self):
        rng = np.random.default_rng(1)
        k = rng.normal(0, 0.1, size=(3, 3, 2, 4)).astype(np.float32)

        def build():
            x = tf1.placeholder(tf.float32, [None, 8, 8, 2], name="x")
            c = tf.nn.conv2d(x, tf.constant(k), strides=[1, 1, 1, 1], padding="SAME")
            r = tf.nn.relu(c)
            p = tf.nn.max_pool2d(r, ksize=2, strides=2, padding="VALID")
            tf.reshape(p, [-1, 4 * 4 * 4], name="out")

        assert_graph_matches(build, {"x": rng.normal(size=(3, 8, 8, 2)).astype(np.float32)}, "out")

    def test_reductions_and_shape_ops(self):
        rng = np.random.default_rng(2)

        def build():
            x = tf1.placeholder(tf.float32, [2, 3, 4], name="x")
            m = tf.reduce_mean(x, axis=[1], keepdims=True)
            t = tf.transpose(x - m, perm=[0, 2, 1])
            c = tf.concat([t, t], axis=2)
            p = tf.pad(c, [[0, 0], [1, 1], [0, 0]])
            tf.reduce_sum(p, axis=[1, 2], name="out")

        assert_graph_matches(build, {"x": rng.normal(size=(2, 3, 4)).astype(np.float32)}, "out")

    def test_batchnorm_inference(self):
        rng = np.random.default_rng(3)
        gamma = rng.normal(1, 0.1, 4).astype(np.float32)
        beta = rng.normal(0, 0.1, 4).astype(np.float32)
        mean = rng.normal(0, 0.3, 4).astype(np.float32)
        var = np.abs(rng.normal(1, 0.1, 4)).astype(np.float32)

        def build():
            x = tf1.placeholder(tf.float32, [None, 5, 5, 4], name="x")
            y, _, _ = tf1.nn.fused_batch_norm(
                x, tf.constant(gamma), tf.constant(beta),
                tf.constant(mean), tf.constant(var), is_training=False, epsilon=1e-3,
            )
            tf.identity(y, name="out")

        assert_graph_matches(build, {"x": rng.normal(size=(2, 5, 5, 4)).astype(np.float32)}, "out", atol=1e-4)

    def test_gather_onehot_cast(self):
        table = np.arange(20, dtype=np.float32).reshape(10, 2)

        def build():
            ids = tf1.placeholder(tf.int32, [None], name="ids")
            e = tf.gather(tf.constant(table), ids)
            oh = tf.one_hot(ids, 10)
            tf.concat([e, tf.cast(oh, tf.float32)], axis=1, name="out")

        assert_graph_matches(build, {"ids": np.array([1, 5, 9], np.int32)}, "out")

    def test_select_and_comparisons(self):
        def build():
            x = tf1.placeholder(tf.float32, [None], name="x")
            tf1.where_v2(tf.greater(x, 0.0), x * 2.0, x - 1.0, name="out")

        assert_graph_matches(build, {"x": np.array([-1.0, 0.5, 3.0], np.float32)}, "out")


def build_mini_bert_encoder(seq=6, vocab=30, d=8, heads=2):
    """One transformer encoder block the way BERT's frozen graph spells it:
    gather embedding, decomposed layer-norm, MHA via batched matmuls,
    erf-GELU feed-forward, residual adds."""
    rng = np.random.default_rng(7)
    f32 = lambda *s: rng.normal(0, 0.08, s).astype(np.float32)
    emb = tf.constant(f32(vocab, d), name="embeddings")
    wq, wk, wv, wo = (tf.constant(f32(d, d)) for _ in range(4))
    w1, w2 = tf.constant(f32(d, 4 * d)), tf.constant(f32(4 * d, d))
    g1 = tf.constant(np.ones(d, np.float32))
    b1 = tf.constant(np.zeros(d, np.float32))

    ids = tf1.placeholder(tf.int32, [None, seq], name="input_ids")
    x = tf.gather(emb, ids)  # (B, T, D)

    def layer_norm(t):
        mu = tf.reduce_mean(t, axis=[-1], keepdims=True)
        var = tf.reduce_mean(tf.math.squared_difference(t, mu), axis=[-1], keepdims=True)
        return (t - mu) * tf.math.rsqrt(var + 1e-6) * g1 + b1

    def split_heads(t):  # (B,T,D) -> (B,H,T,D/H)
        s = tf.reshape(t, [-1, seq, heads, d // heads])
        return tf.transpose(s, [0, 2, 1, 3])

    q, k_, v = split_heads(x @ wq), split_heads(x @ wk), split_heads(x @ wv)
    scores = tf.matmul(q, tf.transpose(k_, [0, 1, 3, 2])) / np.sqrt(d // heads).astype(np.float32)
    att = tf.matmul(tf.nn.softmax(scores), v)               # (B,H,T,hd)
    att = tf.reshape(tf.transpose(att, [0, 2, 1, 3]), [-1, seq, d]) @ wo
    h = layer_norm(x + att)

    def gelu(t):
        return t * 0.5 * (1.0 + tf.math.erf(t / np.sqrt(2.0).astype(np.float32)))

    ff = gelu(h @ w1) @ w2
    out = layer_norm(h + ff)
    tf.identity(out, name="encoder_out")


class TestBertPath:
    def test_mini_bert_encoder_matches_tf(self):
        g = tf1.Graph()
        with g.as_default():
            build_mini_bert_encoder()
        ids = np.random.default_rng(0).integers(0, 30, size=(2, 6)).astype(np.int32)
        want = golden(g, {"input_ids:0": ids}, "encoder_out:0")
        sd = import_graph(g.as_graph_def())
        got = sd.output({"input_ids": ids}, "encoder_out")
        np.testing.assert_allclose(np.asarray(got), want, atol=2e-5, rtol=1e-4)

    def test_fine_tune_imported_encoder(self):
        """BASELINE config 4 shape: import frozen graph, attach a head +
        loss, fine-tune — loss must decrease and weights must move."""
        from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
        from deeplearning4j_tpu.nn.updaters import Adam

        g = tf1.Graph()
        with g.as_default():
            build_mini_bert_encoder()
        sd = import_graph(g.as_graph_def(), trainable=True)
        assert len(sd.variables()) > 0  # frozen weights became variables

        # classification head over mean-pooled encoder output
        pooled = sd.apply("mean", sd._vars["encoder_out"], axis=(1,))
        logits = sd.apply("matmul", pooled, sd.var("head_w", np.random.default_rng(1).normal(0, 0.1, (8, 2)).astype(np.float32)))
        labels = sd.placeholder("labels")
        loss = sd.apply("softmax_cross_entropy", logits, labels)
        sd.set_loss(loss)
        sd.set_training_config(TrainingConfig(updater=Adam(5e-3)))

        rng = np.random.default_rng(2)
        ids = rng.integers(0, 30, size=(8, 6)).astype(np.int32)
        y = np.eye(2, dtype=np.float32)[(ids.sum(axis=1) % 2)]
        losses = [sd.fit_batch({"input_ids": ids, "labels": y}) for _ in range(30)]
        assert losses[-1] < losses[0], losses[::10]


class TestReviewRegressions:
    def test_dilated_conv(self):
        rng = np.random.default_rng(11)
        k = rng.normal(0, 0.1, size=(3, 3, 1, 2)).astype(np.float32)

        def build():
            x = tf1.placeholder(tf.float32, [None, 10, 10, 1], name="x")
            tf.nn.conv2d(x, tf.constant(k), strides=[1, 1, 1, 1],
                         padding="SAME", dilations=[1, 2, 2, 1], name="out")

        assert_graph_matches(build, {"x": rng.normal(size=(2, 10, 10, 1)).astype(np.float32)}, "out")

    def test_padv2_constant_values(self):
        def build():
            x = tf1.placeholder(tf.float32, [2, 2], name="x")
            tf.pad(x, [[0, 0], [1, 1]], constant_values=-9.5, name="out")

        assert_graph_matches(build, {"x": np.ones((2, 2), np.float32)}, "out")

    def test_onehot_on_off_values(self):
        def build():
            ids = tf1.placeholder(tf.int32, [None], name="ids")
            tf.one_hot(ids, 4, on_value=0.0, off_value=-1e4, name="out")

        assert_graph_matches(build, {"ids": np.array([0, 2], np.int32)}, "out")

    def test_slice_minus_one_size(self):
        def build():
            x = tf1.placeholder(tf.float32, [3, 5], name="x")
            tf.slice(x, [1, 0], [-1, 4], name="out")

        assert_graph_matches(build, {"x": np.arange(15, dtype=np.float32).reshape(3, 5)}, "out")

    def test_fetch_addn_and_fused_bn_directly(self):
        rng = np.random.default_rng(12)
        g1v = rng.normal(1, 0.1, 3).astype(np.float32)

        def build():
            x = tf1.placeholder(tf.float32, [None, 2, 2, 3], name="x")
            s = tf.add_n([x, x, x], name="triple")
            y, _, _ = tf1.nn.fused_batch_norm(
                s, tf.constant(g1v), tf.constant(np.zeros(3, np.float32)),
                tf.constant(np.zeros(3, np.float32)), tf.constant(np.ones(3, np.float32)),
                is_training=False, name="bn",
            )

        g = tf1.Graph()
        with g.as_default():
            build()
        feeds = {"x": np.random.default_rng(0).normal(size=(1, 2, 2, 3)).astype(np.float32)}
        want_triple = golden(g, {"x:0": feeds["x"]}, "triple:0")
        want_bn = golden(g, {"x:0": feeds["x"]}, "bn:0")
        sd = import_graph(g.as_graph_def())
        np.testing.assert_allclose(np.asarray(sd.output(feeds, "triple")), want_triple, atol=1e-5)
        np.testing.assert_allclose(np.asarray(sd.output(feeds, "bn")), want_bn, atol=1e-4)

    def test_generated_name_collision_with_tf_names(self):
        """Graph where TF's auto-naming produces add/add_1/... nodes AFTER a
        FusedBatchNorm whose decomposition generates adds internally."""
        rng = np.random.default_rng(13)

        def build():
            x = tf1.placeholder(tf.float32, [None, 2, 2, 2], name="x")
            y, _, _ = tf1.nn.fused_batch_norm(
                x, tf.constant(np.ones(2, np.float32)), tf.constant(np.zeros(2, np.float32)),
                tf.constant(np.zeros(2, np.float32)), tf.constant(np.ones(2, np.float32)),
                is_training=False,
            )
            a = y + 1.0   # TF names these add, add_1, ...
            b = a + 2.0
            c = b + 3.0
            tf.identity(c, name="out")

        assert_graph_matches(build, {"x": rng.normal(size=(1, 2, 2, 2)).astype(np.float32)}, "out", atol=1e-4)


class TestControlFlow:
    """V1 frame reconstruction + V2 functional While/If (SURVEY §3.3
    VarId frames; VERDICT r3 item 5)."""

    def _v1(self, build_fn, feeds, fetch, atol=1e-5):
        tf1.disable_control_flow_v2()
        try:
            return assert_graph_matches(build_fn, feeds, fetch, atol=atol)
        finally:
            tf1.enable_control_flow_v2()

    def test_v1_while_with_capture(self):
        def build():
            x = tf1.placeholder(tf.float32, [4], name="x")
            scale = tf.constant(2.0, name="scale")  # is_constant Enter
            tf1.while_loop(
                lambda i, a: i < 5,
                lambda i, a: (i + 1, a * scale + 1.0),
                [tf.constant(0), x], name="loop",
            )
            # fetch through Exit's consumer
            tf.identity(tf1.get_default_graph()
                        .get_tensor_by_name("loop/Exit_1:0"), name="out")

        self._v1(build, {"x": np.array([1., -2., 3., .5], np.float32)},
                 "out")

    def test_v1_while_dynamic_capture(self):
        def build():
            x = tf1.placeholder(tf.float32, [3], name="x")
            s = tf1.placeholder(tf.float32, [], name="s")  # dynamic capture
            _, acc = tf1.while_loop(
                lambda i, a: i < 4,
                lambda i, a: (i + 1, a + s),
                [tf.constant(0), x], name="loop",
            )
            tf.identity(acc, name="out")

        self._v1(build,
                 {"x": np.zeros(3, np.float32), "s": np.float32(2.5)},
                 "out")

    def test_v1_two_sequential_loops(self):
        def build():
            x = tf1.placeholder(tf.float32, [2], name="x")
            _, a1 = tf1.while_loop(
                lambda i, a: i < 3, lambda i, a: (i + 1, a + 1.0),
                [tf.constant(0), x], name="l1",
            )
            _, a2 = tf1.while_loop(
                lambda i, a: i < 2, lambda i, a: (i + 1, a * 3.0),
                [tf.constant(0), a1], name="l2",
            )
            tf.identity(a2, name="out")

        self._v1(build, {"x": np.array([1., 2.], np.float32)}, "out")

    def test_v1_cond_both_branches(self):
        def build():
            x = tf1.placeholder(tf.float32, [4], name="x")
            y = tf1.cond(tf.reduce_sum(x) > 0.0,
                         lambda: x * 2.0 + 1.0, lambda: x - 3.0,
                         name="branch")
            tf.identity(y, name="out")

        xv = np.array([1., -2., 3., .5], np.float32)
        tf1.disable_control_flow_v2()
        try:
            g = tf1.Graph()
            with g.as_default():
                build()
        finally:
            tf1.enable_control_flow_v2()
        sd = import_graph(g.as_graph_def())
        for v in (xv, -xv):  # exercise BOTH branches
            want = golden(g, {"x:0": v}, "out:0")
            np.testing.assert_allclose(
                np.asarray(sd.output({"x": v}, "out")), want, atol=1e-5)

    def test_v1_cond_const_branch(self):
        def build():
            x = tf1.placeholder(tf.float32, [], name="x")
            y = tf1.cond(x > 0.0,
                         lambda: tf.constant(7.0), lambda: x * 2.0,
                         name="branch")
            tf.identity(y, name="out")

        tf1.disable_control_flow_v2()
        try:
            g = tf1.Graph()
            with g.as_default():
                build()
        finally:
            tf1.enable_control_flow_v2()
        sd = import_graph(g.as_graph_def())
        for v in (np.float32(3.0), np.float32(-3.0)):
            want = golden(g, {"x:0": v}, "out:0")
            np.testing.assert_allclose(
                np.asarray(sd.output({"x": v}, "out")), want, atol=1e-5)

    def test_v2_multi_output_if(self):
        """Tout is a list(type) attr; a decode gap here once bound only the
        first If output (r4 review finding)."""
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )

        @tf.function
        def f(x):
            a, b = tf.cond(tf.reduce_sum(x) > 0.0,
                           lambda: (x * 2.0, x + 1.0),
                           lambda: (x - 1.0, x * 3.0))
            return a + b

        cfn = f.get_concrete_function(tf.TensorSpec([3], tf.float32))
        frozen = convert_variables_to_constants_v2(
            cfn, lower_control_flow=False)
        sd = import_graph(frozen.graph.as_graph_def().SerializeToString())
        for v in (np.array([1., 2., 3.], np.float32),
                  np.array([-1., -2., -3.], np.float32)):
            want = f(tf.constant(v)).numpy()
            np.testing.assert_allclose(
                np.asarray(sd.output({"x": v}, "Identity")), want,
                atol=1e-5)

    def test_v2_functional_while_if(self):
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )

        @tf.function
        def f(x):
            _, acc = tf.while_loop(
                lambda i, a: i < 5,
                lambda i, a: (i + 1, a * 2.0 + 1.0),
                [tf.constant(0), x],
            )
            return tf.cond(tf.reduce_sum(acc) > 0.0,
                           lambda: acc * 2.0, lambda: acc - 1.0)

        cfn = f.get_concrete_function(tf.TensorSpec([4], tf.float32))
        frozen = convert_variables_to_constants_v2(
            cfn, lower_control_flow=False)
        raw = frozen.graph.as_graph_def().SerializeToString()
        sd = import_graph(raw)  # exercises the self-contained codec too
        for v in (np.array([1., -2., 3., .5], np.float32),
                  np.array([-9., -2., -3., -.5], np.float32)):
            want = f(tf.constant(v)).numpy()
            np.testing.assert_allclose(
                np.asarray(sd.output({"x": v}, "Identity")), want,
                atol=1e-5)


class TestErrorPaths:
    def test_unsupported_op_inside_loop_names_body(self):
        tf1.disable_control_flow_v2()
        try:
            g = tf1.Graph()
            with g.as_default():
                x = tf1.placeholder(tf.complex64, [4], name="x")
                tf1.while_loop(
                    lambda i, a: i < 2,
                    lambda i, a: (i + 1, tf1.fft(a)),
                    [tf.constant(0), x], name="loop",
                )
        finally:
            tf1.enable_control_flow_v2()
        with pytest.raises(TFImportError, match="while frame"):
            import_graph(g.as_graph_def())

    def test_unsupported_op_named(self):
        g = tf1.Graph()
        with g.as_default():
            x = tf1.placeholder(tf.complex64, [4], name="x")
            tf1.fft(x, name="out")
        with pytest.raises(TFImportError, match="FFT"):
            import_graph(g.as_graph_def())

    def test_dynamic_reshape_rejected(self):
        g = tf1.Graph()
        with g.as_default():
            x = tf1.placeholder(tf.float32, [None, 4], name="x")
            s = tf1.placeholder(tf.int32, [2], name="s")
            tf.reshape(x, s, name="out")
        with pytest.raises(TFImportError, match="constant"):
            import_graph(g.as_graph_def())

    def test_onnx_facade_delegates(self):
        # ONNX import is real now (modelimport/onnx.py); the facade passes
        # through — a missing file surfaces as the OS error
        with pytest.raises(FileNotFoundError):
            import_onnx("/tmp/nonexistent.onnx")

    def test_facade_from_file(self, tmp_path):
        g = tf1.Graph()
        with g.as_default():
            x = tf1.placeholder(tf.float32, [None, 2], name="x")
            tf.identity(x * 2.0, name="out")
        p = tmp_path / "g.pb"
        p.write_bytes(g.as_graph_def().SerializeToString())
        sd = TFGraphMapper.import_graph(str(p))
        out = sd.output({"x": np.ones((1, 2), np.float32)}, "out")
        np.testing.assert_allclose(np.asarray(out), [[2.0, 2.0]])


class TestAdvisorRegressions:
    """Round-1 advisor findings (ADVICE.md)."""

    def test_trainable_promotion_through_identity_read(self):
        """Frozen graphs put weights behind Const -> Identity('w/read') ->
        consumer (the convert_variables_to_constants pattern); trainable=True
        must still promote them to variables (ADVICE.md high)."""
        rng = np.random.default_rng(7)
        w = rng.normal(0, 0.3, size=(4, 3)).astype(np.float32)

        g = tf1.Graph()
        with g.as_default():
            x = tf1.placeholder(tf.float32, [None, 4], name="x")
            wv = tf1.Variable(w, name="w")
            tf.matmul(x, wv, name="out")
            with tf1.Session(graph=g) as sess:
                sess.run(tf1.global_variables_initializer())
                frozen = tf1.graph_util.convert_variables_to_constants(
                    sess, g.as_graph_def(), ["out"]
                )

        sd = import_graph(frozen, trainable=True)
        assert len(sd.variables()) > 0, "no weights promoted through Identity read"

        # and they actually train
        from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
        from deeplearning4j_tpu.nn.updaters import Sgd

        out = sd._vars["out"]
        loss = sd.apply("mean", sd.apply("square", out))
        sd.set_loss(loss)
        sd.set_training_config(TrainingConfig(updater=Sgd(0.5)))
        before = [sd.get_value(n).copy() for n in sd.variables()]
        xb = rng.normal(size=(8, 4)).astype(np.float32)
        for _ in range(3):
            sd.fit_batch({"x": xb})
        after = [sd.get_value(n) for n in sd.variables()]
        moved = any(not np.allclose(b, a) for b, a in zip(before, after))
        assert moved, "promoted variables did not move during fine-tune"

    def test_fused_batchnorm_training_mode_rejected(self):
        """Training-mode FusedBatchNorm has unpopulated mean/var inputs; the
        import must fail loudly, not silently mis-normalize (ADVICE.md low)."""
        g = tf1.Graph()
        with g.as_default():
            x = tf1.placeholder(tf.float32, [None, 4, 4, 2], name="x")
            scale = tf.constant(np.ones(2, np.float32))
            offset = tf.constant(np.zeros(2, np.float32))
            tf1.nn.fused_batch_norm(x, scale, offset, name="bn", is_training=True)
        with pytest.raises(TFImportError, match="is_training"):
            import_graph(g.as_graph_def())

    def test_stop_gradient_const_never_promoted(self):
        """tf.stop_gradient over a frozen weight stays a constant even with
        trainable=True (the author explicitly froze it)."""
        w = np.ones((3, 2), np.float32)
        g = tf1.Graph()
        with g.as_default():
            x = tf1.placeholder(tf.float32, [None, 3], name="x")
            frozen_w = tf.stop_gradient(tf.constant(w), name="wf")
            tf.matmul(x, frozen_w, name="out")
        sd = import_graph(g.as_graph_def(), trainable=True)
        assert sd.variables() == [], "stop_gradient'd const was promoted"

    def test_single_promotion_per_const(self):
        """A Const consumed both directly and through Identity must yield ONE
        trainable variable, not two drifting copies."""
        w = np.full((2, 2), 3.0, np.float32)
        g = tf1.Graph()
        with g.as_default():
            x = tf1.placeholder(tf.float32, [None, 2], name="x")
            wc = tf.constant(w, name="w")
            rd = tf.identity(wc, name="w/read")
            a = tf.matmul(x, rd, name="a")
            tf.add(a, tf.matmul(x, wc), name="out")
        sd = import_graph(g.as_graph_def(), trainable=True)
        assert len(sd.variables()) == 1, sd.variables()
        got = sd.output({"x": np.ones((1, 2), np.float32)}, "out")
        np.testing.assert_allclose(np.asarray(got), [[12.0, 12.0]])


class TestRound4OpTail:
    """StridedSlice/Shape/Fill/Range/Unpack/Cumsum/Round/ZerosLike/
    L2Loss/GatherNd mappers."""

    def test_strided_slice_variants(self):
        def build():
            x = tf1.placeholder(tf.float32, [4, 6, 8], name="x")
            a = x[:, 1:5:2, ::-1]            # slices + negative stride
            b = x[:, 0, 2:]                  # shrink axis
            tf.identity(a, name="a")
            tf.identity(b, name="b")

        g = tf1.Graph()
        with g.as_default():
            build()
        xv = np.random.default_rng(0).normal(size=(4, 6, 8)).astype(np.float32)
        sd = import_graph(g.as_graph_def())
        for fetch in ("a", "b"):
            want = golden(g, {"x:0": xv}, f"{fetch}:0")
            np.testing.assert_allclose(
                np.asarray(sd.output({"x": xv}, fetch)), want, atol=1e-6)

    def test_shape_fill_range_folding(self):
        def build():
            c = tf.constant(np.ones((3, 5), np.float32), name="c")
            s = tf.shape(c, name="s")
            f = tf.fill([2, 3], 7.0, name="f")
            r = tf.range(0.0, 5.0, 1.0, name="r")
            tf.identity(tf.cast(s, tf.float32), name="s_out")
            tf.identity(f, name="f_out")
            tf.identity(r, name="r_out")

        g = tf1.Graph()
        with g.as_default():
            build()
        sd = import_graph(g.as_graph_def())
        np.testing.assert_allclose(np.asarray(sd.output({}, "s_out")), [3, 5])
        np.testing.assert_allclose(np.asarray(sd.output({}, "f_out")),
                                   np.full((2, 3), 7.0))
        np.testing.assert_allclose(np.asarray(sd.output({}, "r_out")),
                                   np.arange(5.0))

    def test_unpack_cumsum_round_l2(self):
        def build():
            x = tf1.placeholder(tf.float32, [3, 4], name="x")
            a, b, c = tf.unstack(x, axis=0)
            cs = tf.cumsum(x, axis=1)
            tf.identity(b, name="mid")
            tf.identity(cs, name="cs")
            tf.identity(tf.round(x), name="rnd")
            tf.identity(tf.nn.l2_loss(x), name="l2")
            tf.identity(tf.zeros_like(x) + tf.ones_like(x), name="zl")

        g = tf1.Graph()
        with g.as_default():
            build()
        xv = np.random.default_rng(1).normal(size=(3, 4)).astype(np.float32)
        sd = import_graph(g.as_graph_def())
        for fetch in ("mid", "cs", "rnd", "l2", "zl"):
            want = golden(g, {"x:0": xv}, f"{fetch}:0")
            np.testing.assert_allclose(
                np.asarray(sd.output({"x": xv}, fetch)), want,
                atol=1e-5, rtol=1e-5)

    def test_gather_nd(self):
        def build():
            x = tf1.placeholder(tf.float32, [4, 5], name="x")
            idx = tf.constant([[0, 1], [3, 4]], tf.int32)
            tf.identity(tf.gather_nd(x, idx), name="out")

        g = tf1.Graph()
        with g.as_default():
            build()
        xv = np.random.default_rng(2).normal(size=(4, 5)).astype(np.float32)
        sd = import_graph(g.as_graph_def())
        want = golden(g, {"x:0": xv}, "out:0")
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": xv}, "out")), want, atol=1e-6)

    def test_resize_bilinear_half_pixel(self):
        def build():
            x = tf1.placeholder(tf.float32, [2, 4, 4, 3], name="x")
            y = tf1.image.resize_bilinear(x, [8, 8],
                                          half_pixel_centers=True)
            tf.identity(y, name="out")

        assert_graph_matches(
            build,
            {"x": np.random.default_rng(3).normal(
                size=(2, 4, 4, 3)).astype(np.float32)},
            "out", atol=1e-5)

    def test_resize_default_mode_rejected(self):
        g = tf1.Graph()
        with g.as_default():
            x = tf1.placeholder(tf.float32, [1, 4, 4, 1], name="x")
            tf.identity(tf1.image.resize_bilinear(x, [8, 8]), name="out")
        with pytest.raises(TFImportError, match="half_pixel_centers"):
            import_graph(g.as_graph_def())

    def test_unstack_negative_axis(self):
        def build():
            x = tf1.placeholder(tf.float32, [2, 3, 4], name="x")
            parts = tf.unstack(x, axis=-1)
            tf.identity(parts[2], name="out")

        assert_graph_matches(
            build,
            {"x": np.random.default_rng(5).normal(
                size=(2, 3, 4)).astype(np.float32)},
            "out")


class TestSourceBackedSerde:
    """Imported graphs with control flow checkpoint by shipping the
    source bytes (save) and re-importing them (load) — round 4."""

    def test_while_graph_roundtrips_through_zip(self, tmp_path):
        tf1.disable_control_flow_v2()
        try:
            g = tf1.Graph()
            with g.as_default():
                x = tf1.placeholder(tf.float32, [3], name="x")
                _, acc = tf1.while_loop(
                    lambda i, a: i < 4,
                    lambda i, a: (i + 1, a * 2.0 + 1.0),
                    [tf.constant(0), x], name="loop",
                )
                tf.identity(acc, name="out")
        finally:
            tf1.enable_control_flow_v2()
        raw = g.as_graph_def().SerializeToString()
        sd = import_graph(raw)
        xv = np.array([1.0, -2.0, 0.5], np.float32)
        want = np.asarray(sd.output({"x": xv}, "out"))
        p = str(tmp_path / "cf.sd.zip")
        sd.save(p)

        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        sd2 = SameDiff.load(p)
        np.testing.assert_allclose(
            np.asarray(sd2.output({"x": xv}, "out")), want, atol=1e-6)

    def test_finetuned_import_with_head_roundtrips(self, tmp_path):
        """The BASELINE-config-4 shape: import trainable, attach a loss
        head, fine-tune, checkpoint, resume — values and post-import ops
        must survive."""
        from deeplearning4j_tpu.autodiff.samediff import (
            SameDiff, TrainingConfig)
        from deeplearning4j_tpu.nn.updaters import Adam

        g = tf1.Graph()
        with g.as_default():
            build_mini_bert_encoder()
        sd = import_graph(g.as_graph_def(), trainable=True)
        rng = np.random.default_rng(0)
        pooled = sd.apply("mean", sd._vars["encoder_out"], axis=(1,))
        head_w = sd.var("head_w",
                        rng.normal(0, 0.1, (8, 2)).astype(np.float32))
        logits = sd.apply("matmul", pooled, head_w)
        labels = sd.placeholder("labels")
        sd.set_loss(sd.apply("softmax_cross_entropy", logits, labels,
                             name="fine_loss"))
        sd.set_training_config(TrainingConfig(updater=Adam(5e-3)))
        ids = rng.integers(0, 30, (4, 6)).astype(np.int32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)]
        for _ in range(3):
            sd.fit_batch({"input_ids": ids, "labels": y})
        want = np.asarray(sd.output({"input_ids": ids}, "encoder_out"))

        p = str(tmp_path / "ft.sd.zip")
        sd.save(p)
        sd2 = SameDiff.load(p)
        got = np.asarray(sd2.output({"input_ids": ids}, "encoder_out"))
        np.testing.assert_allclose(got, want, atol=1e-5)
        # the post-import head survived and training RESUMES
        assert "head_w" in sd2.variables()
        sd2.set_training_config(TrainingConfig(updater=Adam(5e-3)))
        l2 = sd2.fit_batch({"input_ids": ids, "labels": y})
        assert np.isfinite(l2)

    def test_hand_built_control_flow_still_rejects(self, tmp_path):
        import pytest as _pytest

        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        sd = SameDiff()
        x = sd.placeholder("x")
        sd.while_loop(lambda v: (v < 5).all(), lambda v: (v + 1,), x)
        with _pytest.raises(ValueError, match="rebuild the graph"):
            sd.save(str(tmp_path / "nope.zip"))

    def test_split_and_splitv(self):
        def build():
            x = tf1.placeholder(tf.float32, [2, 6], name="x")
            a, b2, c = tf.split(x, 3, axis=1)
            d, e = tf.split(x, [2, 4], axis=1)
            tf.identity(b2, name="mid")
            tf.identity(tf.concat([a, c], 1), name="outer")
            tf.identity(e - d[:, :1], name="v")

        assert_graph_matches(
            build,
            {"x": np.random.default_rng(7).normal(
                size=(2, 6)).astype(np.float32)},
            "mid")
        # also check the other fetches wire correctly
        g = tf1.Graph()
        with g.as_default():
            build()
        xv = np.random.default_rng(8).normal(size=(2, 6)).astype(np.float32)
        sd = import_graph(g.as_graph_def())
        for fetch in ("outer", "v"):
            want = golden(g, {"x:0": xv}, f"{fetch}:0")
            np.testing.assert_allclose(
                np.asarray(sd.output({"x": xv}, fetch)), want, atol=1e-6)


class TestNestedFrames:
    """Nested V1 while frames reconstruct recursively (round 4 — the
    body sub-interpreter reruns the same structural pass)."""

    def _v1_graph(self, build):
        tf1.disable_control_flow_v2()
        try:
            g = tf1.Graph()
            with g.as_default():
                build()
        finally:
            tf1.enable_control_flow_v2()
        return g

    def test_two_level_nested_while(self):
        def build():
            x = tf1.placeholder(tf.float32, [3], name="x")

            def outer_body(i, a):
                _, a2 = tf1.while_loop(
                    lambda j, b: j < 2,
                    lambda j, b: (j + 1, b * 2.0 + 1.0),
                    [tf.constant(0), a], name="inner",
                )
                return i + 1, a2 - 0.5

            _, acc = tf1.while_loop(lambda i, a: i < 3, outer_body,
                                    [tf.constant(0), x], name="outer")
            tf.identity(acc, name="out")

        g = self._v1_graph(build)
        xv = np.array([1.0, -0.5, 2.0], np.float32)
        want = golden(g, {"x:0": xv}, "out:0")
        sd = import_graph(g.as_graph_def())
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": xv}, "out")), want, atol=1e-5)

    def test_nested_while_with_outer_capture(self):
        def build():
            x = tf1.placeholder(tf.float32, [2], name="x")
            s = tf1.placeholder(tf.float32, [], name="s")

            def outer_body(i, a):
                _, a2 = tf1.while_loop(
                    lambda j, b: j < 2,
                    lambda j, b: (j + 1, b + s),   # captures OUTER tensor
                    [tf.constant(0), a], name="inner",
                )
                return i + 1, a2 * 0.5

            _, acc = tf1.while_loop(lambda i, a: i < 2, outer_body,
                                    [tf.constant(0), x], name="outer")
            tf.identity(acc, name="out")

        g = self._v1_graph(build)
        xv = np.array([4.0, -2.0], np.float32)
        sv = np.float32(3.0)
        want = golden(g, {"x:0": xv, "s:0": sv}, "out:0")
        sd = import_graph(g.as_graph_def())
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": xv, "s": sv}, "out")), want,
            atol=1e-5)

    def test_three_level_nesting(self):
        def build():
            x = tf1.placeholder(tf.float32, [], name="x")

            def mid_body(j, b):
                _, b2 = tf1.while_loop(
                    lambda k, c: k < 2,
                    lambda k, c: (k + 1, c + 1.0),
                    [tf.constant(0), b], name="l3",
                )
                return j + 1, b2

            def outer_body(i, a):
                _, a2 = tf1.while_loop(lambda j, b: j < 2, mid_body,
                                       [tf.constant(0), a], name="l2")
                return i + 1, a2 * 1.5

            _, acc = tf1.while_loop(lambda i, a: i < 2, outer_body,
                                    [tf.constant(0), x], name="l1")
            tf.identity(acc, name="out")

        g = self._v1_graph(build)
        want = golden(g, {"x:0": np.float32(1.0)}, "out:0")
        sd = import_graph(g.as_graph_def())
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": np.float32(1.0)}, "out")), want,
            atol=1e-5)

    def test_cond_inside_while_body(self):
        """tf.cond nested in a while body: the cond's Switch/Merge stay
        interior and the body sub-pass reconstructs them (r4 review
        finding — these used to be stripped as loop structure)."""
        def build():
            x = tf1.placeholder(tf.float32, [3], name="x")

            def body(i, a):
                a2 = tf1.cond(tf.reduce_sum(a) > 10.0,
                              lambda: a * 0.5, lambda: a + 1.0)
                return i + 1, a2

            _, acc = tf1.while_loop(lambda i, a: i < 4, body,
                                    [tf.constant(0), x], name="loop")
            tf.identity(acc, name="out")

        g = self._v1_graph(build)
        sd = import_graph(g.as_graph_def())
        for xv in (np.array([1.0, 2.0, 3.0], np.float32),
                   np.array([8.0, 9.0, 7.0], np.float32)):
            want = golden(g, {"x:0": xv}, "out:0")
            np.testing.assert_allclose(
                np.asarray(sd.output({"x": xv}, "out")), want, atol=1e-5)


class TestDifferentiableImportedLoops:
    """Round 5 (VERDICT r4 missing #1): statically-counted imported loops
    lower to lax.scan and support reverse-mode autodiff; dynamic loops
    keep the while_loop fallback unless loop_trip_bound is given."""

    def _v1_graph(self, build):
        tf1.disable_control_flow_v2()
        try:
            g = tf1.Graph()
            with g.as_default():
                build()
        finally:
            tf1.enable_control_flow_v2()
        return g

    @staticmethod
    def _while_attrs(sd):
        return [n.attrs for n in sd._ops if n.op == "_while"]

    def test_v1_static_counter_lowered_to_exact_scan(self):
        def build():
            x = tf1.placeholder(tf.float32, [3], name="x")
            tf1.while_loop(lambda i, a: i < 7,
                           lambda i, a: (i + 1, a * 2.0),
                           [tf.constant(0), x], name="loop")
            tf.identity(tf1.get_default_graph()
                        .get_tensor_by_name("loop/Exit_1:0"), name="out")

        g = self._v1_graph(build)
        xv = np.array([1.0, -1.0, 0.5], np.float32)
        want = golden(g, {"x:0": xv}, "out:0")
        sd = import_graph(g.as_graph_def())
        (w,) = self._while_attrs(sd)
        assert w["max_trip"] == 7 and w["exact_trip"] is True
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": xv}, "out")), want, atol=1e-5)

    def test_v1_countdown_and_step2_counters(self):
        """Non-unit stride and descending counters infer exactly too."""
        def build():
            x = tf1.placeholder(tf.float32, [2], name="x")
            tf1.while_loop(lambda i, a: i > 0,
                           lambda i, a: (i - 2, a + 1.0),
                           [tf.constant(9), x], name="loop")
            tf.identity(tf1.get_default_graph()
                        .get_tensor_by_name("loop/Exit_1:0"), name="out")

        g = self._v1_graph(build)
        sd = import_graph(g.as_graph_def())
        (w,) = self._while_attrs(sd)
        assert w["max_trip"] == 5 and w["exact_trip"] is True  # 9,7,5,3,1
        want = golden(g, {"x:0": np.zeros(2, np.float32)}, "out:0")
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": np.zeros(2, np.float32)}, "out")),
            want, atol=1e-5)

    def test_v1_data_dependent_pred_falls_back_to_while(self):
        def build():
            x = tf1.placeholder(tf.float32, [], name="x")
            tf1.while_loop(lambda a: a < 100.0, lambda a: a * 2.0,
                           [x], name="loop")
            tf.identity(tf1.get_default_graph()
                        .get_tensor_by_name("loop/Exit:0"), name="out")

        g = self._v1_graph(build)
        sd = import_graph(g.as_graph_def())
        (w,) = self._while_attrs(sd)
        assert w["max_trip"] is None and w["exact_trip"] is False
        want = golden(g, {"x:0": np.float32(3.0)}, "out:0")
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": np.float32(3.0)}, "out")), want)

    def test_v1_dynamic_loop_with_trip_bound_differentiates(self):
        """loop_trip_bound lowers a data-dependent loop to scan+mask:
        same forward values, and gradients flow."""
        import jax
        import jax.numpy as jnp

        def build():
            x = tf1.placeholder(tf.float32, [], name="x")
            tf1.while_loop(lambda a: a < 100.0, lambda a: a * 2.0,
                           [x], name="loop")
            tf.identity(tf1.get_default_graph()
                        .get_tensor_by_name("loop/Exit:0"), name="out")

        g = self._v1_graph(build)
        sd = import_graph(g.as_graph_def(), loop_trip_bound=16)
        (w,) = self._while_attrs(sd)
        assert w["max_trip"] == 16 and w["exact_trip"] is False
        for xv in (3.0, 0.5, 150.0):
            want = golden(g, {"x:0": np.float32(xv)}, "out:0")
            np.testing.assert_allclose(
                np.asarray(sd.output({"x": np.float32(xv)}, "out")), want)

        def f(xv):
            (o,) = sd._execute({**sd._values, "x": xv}, ("out",))
            return o

        # d(out)/dx = 2^trips; for x=3: 3->6->12->24->48->96->192, 6 trips
        assert float(jax.grad(f)(jnp.float32(3.0))) == 64.0

    def test_trip_bound_reaches_nested_function_loops(self):
        """loop_trip_bound must propagate into FunctionDef sub-importers
        (r5 review finding: it was reset to None, leaving inner dynamic
        loops forward-only)."""
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )

        @tf.function
        def inner(x):
            return tf.while_loop(lambda a: tf.reduce_sum(a) < 10.0,
                                 lambda a: a * 2.0, [x])[0]

        @tf.function
        def fn(x):
            return inner(x) + 1.0

        cfn = fn.get_concrete_function(tf.TensorSpec([2], tf.float32))
        frozen = convert_variables_to_constants_v2(
            cfn, lower_control_flow=False)
        sd = import_graph(frozen.graph.as_graph_def(), loop_trip_bound=12)
        xv = np.array([0.5, 0.7], np.float32)
        want = fn(tf.constant(xv)).numpy()
        ph = [k for k in sd._placeholders][0]
        np.testing.assert_allclose(
            np.asarray(sd.output({ph: xv}, "Identity")), want, rtol=1e-6)
        # the nested loop's while node lives in a sub-SameDiff; assert on
        # behavior instead: gradients flow because it scanned
        import jax
        import jax.numpy as jnp

        def f(v):
            (o,) = sd._execute({**sd._values, ph: v}, ("Identity",))
            return jnp.sum(o)

        g = jax.grad(f)(jnp.asarray(xv))
        assert np.all(np.isfinite(np.asarray(g)))
        assert float(jnp.abs(g).max()) > 0

    def test_v2_functional_while_static_trip(self):
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )

        @tf.function
        def fn(x):
            i = tf.constant(0)
            _, acc = tf.while_loop(lambda i, a: i < 5,
                                   lambda i, a: (i + 1, tf.tanh(a) + a),
                                   [i, x])
            return acc

        cfn = fn.get_concrete_function(tf.TensorSpec([4], tf.float32))
        frozen = convert_variables_to_constants_v2(
            cfn, lower_control_flow=False)
        sd = import_graph(frozen.graph.as_graph_def())
        (w,) = self._while_attrs(sd)
        assert w["max_trip"] == 5 and w["exact_trip"] is True
        xv = np.array([0.1, -0.2, 0.3, 0.4], np.float32)
        want = fn(tf.constant(xv)).numpy()
        ph = [k for k in sd._placeholders][0]
        np.testing.assert_allclose(
            np.asarray(sd.output({ph: xv}, sd.onnx_outputs[0]
                                 if hasattr(sd, "onnx_outputs") else
                                 "Identity")),
            want, rtol=1e-5, atol=1e-5)

    def test_trainable_loop_capture_promotes_and_trains(self):
        """A float weight matrix captured by the loop body promotes to a
        trainable variable (not a baked static), and its gradient through
        the scanned loop matches finite differences."""
        import jax
        import jax.numpy as jnp

        rng = np.random.default_rng(3)
        wv = (rng.normal(size=(3, 3)) * 0.5).astype(np.float32)

        def build():
            x = tf1.placeholder(tf.float32, [2, 3], name="x")
            wl = tf.constant(wv, name="W")
            tf1.while_loop(lambda i, a: i < 4,
                           lambda i, a: (i + 1, tf.tanh(tf.matmul(a, wl))),
                           [tf.constant(0), x], name="loop")
            tf.identity(tf1.get_default_graph()
                        .get_tensor_by_name("loop/Exit_1:0"), name="out")

        g = self._v1_graph(build)
        sd = import_graph(g.as_graph_def(), trainable=True)
        assert "W" in sd._trainable
        (w,) = self._while_attrs(sd)
        assert w["max_trip"] == 4 and w["exact_trip"] is True

        xv = rng.normal(size=(2, 3)).astype(np.float32)
        want = golden(g, {"x:0": xv}, "out:0")
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": xv}, "out")), want, atol=1e-5)

        def loss(wval):
            (o,) = sd._execute(
                {**sd._values, "W": wval, "x": jnp.asarray(xv)}, ("out",))
            return jnp.sum(o ** 2)

        gw = jax.grad(loss)(jnp.asarray(wv))
        eps = 1e-3
        e = np.zeros_like(wv)
        e[1, 2] = eps
        fd = (loss(jnp.asarray(wv + e)) - loss(jnp.asarray(wv - e))) / (2 * eps)
        np.testing.assert_allclose(float(gw[1, 2]), float(fd), atol=1e-2)

    def test_nested_static_loops_both_scan(self):
        def build():
            x = tf1.placeholder(tf.float32, [2], name="x")

            def outer_body(i, a):
                _, a2 = tf1.while_loop(lambda j, b: j < 3,
                                       lambda j, b: (j + 1, b + 1.0),
                                       [tf.constant(0), a], name="inner")
                return i + 1, a2 * 1.5

            _, acc = tf1.while_loop(lambda i, a: i < 2, outer_body,
                                    [tf.constant(0), x], name="outer")
            tf.identity(acc, name="out")

        g = self._v1_graph(build)
        sd = import_graph(g.as_graph_def())
        (w,) = self._while_attrs(sd)       # outer frame: top-level node
        assert w["max_trip"] == 2 and w["exact_trip"] is True
        want = golden(g, {"x:0": np.ones(2, np.float32)}, "out:0")
        np.testing.assert_allclose(
            np.asarray(sd.output({"x": np.ones(2, np.float32)}, "out")),
            want, atol=1e-5)
