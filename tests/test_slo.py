"""SLO burn-rate engine (ISSUE 13): declarative objectives over the
MetricsRegistry, multi-window burn alerting with an injectable clock,
gauge exposition, the /healthz + /v1/status + /api/slo joins, the fleet
push, and the scrape's meta-observability."""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.observe.metrics import MetricsRegistry, registry
from deeplearning4j_tpu.observe.slo import (
    BurnWindow,
    SLObjective,
    SLOEngine,
    active_engine,
)

pytestmark = pytest.mark.slo

WINDOWS = (BurnWindow(10.0, 10.0), BurnWindow(60.0, 2.0))


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _engine(reg, objectives=None, windows=WINDOWS):
    clock = FakeClock()
    eng = SLOEngine(
        objectives or [SLObjective.availability("avail", target=0.99,
                                                family="t_requests_total")],
        windows=windows, clock=clock, registry=reg,
    )
    return eng, clock


# -- objective declaration ---------------------------------------------------


class TestObjectives:
    def test_target_must_be_a_fraction(self):
        with pytest.raises(ValueError):
            SLObjective.availability("bad", target=99.9)

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            SLObjective(name="x", target=0.9, kind="saturation")

    def test_throughput_needs_a_positive_floor(self):
        with pytest.raises(ValueError):
            SLObjective(name="x", target=0.9, kind="throughput")
        with pytest.raises(ValueError):
            SLObjective.throughput("x", target=0.9, floor_per_s=0.0)

    def test_duplicate_names_rejected(self):
        o = SLObjective.availability("a", target=0.9)
        with pytest.raises(ValueError):
            SLOEngine([o, o])

    def test_budget_is_one_minus_target(self):
        assert SLObjective.availability("a", target=0.999).budget == \
            pytest.approx(0.001)


# -- burn-rate evaluation ----------------------------------------------------


class TestBurnRates:
    def test_healthy_traffic_burns_zero(self):
        reg = MetricsRegistry()
        c = reg.counter("t_requests_total")
        eng, clock = _engine(reg)
        for t in range(0, 70, 5):
            clock.t = float(t)
            c.inc(100, outcome="ok")
            st = eng.sample()["avail"]
        assert st["burn"] == {"10s": 0.0, "60s": 0.0}
        assert not st["alert"]
        assert st["budget_remaining"] == 1.0

    def test_zero_traffic_burns_zero(self):
        reg = MetricsRegistry()
        reg.counter("t_requests_total")
        eng, clock = _engine(reg)
        for t in (0.0, 30.0, 120.0):
            clock.t = t
            st = eng.sample()["avail"]
        assert st["burn"] == {"10s": 0.0, "60s": 0.0}
        assert not st["alert"]

    def test_overload_fires_within_fast_window_and_clears(self):
        """The acceptance shape: induced overload -> the fast-window
        alert fires within one fast window; recovery -> it clears
        within one fast window (not one SLOW window)."""
        reg = MetricsRegistry()
        c = reg.counter("t_requests_total")
        eng, clock = _engine(reg)
        for t in range(0, 60, 5):                  # healthy baseline
            clock.t = float(t)
            c.inc(100, outcome="ok")
            eng.sample()
        fired_at = None
        for t in range(60, 120, 2):                # 50% errors
            clock.t = float(t)
            c.inc(50, outcome="ok")
            c.inc(50, outcome="error")
            if eng.sample()["avail"]["alert"] and fired_at is None:
                fired_at = t
        assert fired_at is not None
        assert fired_at - 60 <= WINDOWS[0].seconds     # within fast window
        cleared_at = None
        for t in range(120, 200, 2):               # recovery
            clock.t = float(t)
            c.inc(100, outcome="ok")
            if not eng.sample()["avail"]["alert"] and cleared_at is None:
                cleared_at = t
        assert cleared_at is not None
        assert cleared_at - 120 <= WINDOWS[0].seconds + 2
        st = eng.state()["avail"]
        assert st["alerts_total"] == 1             # one rising edge
        assert st["budget_remaining"] < 0          # budget was blown

    def test_short_blip_does_not_page(self):
        """The slow window is the blip filter: a burst shorter than its
        threshold share must not fire the multi-window alert."""
        reg = MetricsRegistry()
        c = reg.counter("t_requests_total")
        eng, clock = _engine(
            reg, windows=(BurnWindow(10.0, 5.0), BurnWindow(300.0, 30.0)),
        )
        for t in range(0, 300, 5):
            clock.t = float(t)
            if t == 150:                            # one bad tick
                c.inc(10, outcome="error")
            c.inc(100, outcome="ok")
            st = eng.sample()["avail"]
            assert not st["alert"], f"paged on a blip at t={t}"
        assert st["alerts_total"] == 0

    def test_latency_objective_reads_histogram_buckets(self):
        reg = MetricsRegistry()
        h = reg.histogram("t_latency_seconds", buckets=(0.1, 0.25, 1.0))
        eng, clock = _engine(reg, objectives=[
            SLObjective.latency("lat", target=0.9, threshold_s=0.25,
                                family="t_latency_seconds"),
        ])
        eng.sample()                                # empty baseline
        for _ in range(90):
            h.observe(0.05)                         # good
        for _ in range(10):
            h.observe(0.5)                          # bad
        clock.t = 5.0
        st = eng.sample()["lat"]
        assert st["good"] == 90 and st["bad"] == 10
        # 10% bad over a 10% budget = burn exactly 1.0
        assert st["burn"]["10s"] == pytest.approx(1.0)

    def test_count_le_and_sum_series_primitives(self):
        reg = MetricsRegistry()
        h = reg.histogram("t_h_seconds", buckets=(0.1, 0.25, 1.0))
        for v in (0.05, 0.1, 0.2, 0.9, 3.0):
            h.observe(v)
        assert h.count_le(0.25) == 3         # 0.05, 0.1, 0.2
        assert h.count_le(0.05) == 0         # below the first bound
        # the 3.0 observation sits in the +Inf overflow bucket: its
        # magnitude is unknown, so it is never counted as <= anything
        assert h.count_le(10.0) == 4
        c = reg.counter("t_c_total")
        c.inc(3, outcome="ok", route="a")
        c.inc(2, outcome="ok", route="b")
        c.inc(1, outcome="error", route="a")
        assert c.sum_series() == 6
        assert c.sum_series(outcome="ok") == 5
        assert c.sum_series(route="a") == 4
        assert c.sum_series(outcome="error", route="a") == 1


# -- throughput objectives (ISSUE 17) ----------------------------------------


class TestThroughputBurn:
    """The generation-plane rate floor: burn is the fractional deficit
    below floor_per_s over the budget, gated by demand so a quiet
    replica never pages."""

    def _tp(self, reg):
        clock = FakeClock()
        eng = SLOEngine(
            [SLObjective.throughput(
                "tps", target=0.95, floor_per_s=100.0,
                family="t_tokens_total",
                demand_family="t_admitted_total")],
            windows=WINDOWS, clock=clock, registry=reg,
        )
        return eng, clock

    def test_meeting_the_floor_burns_zero(self):
        reg = MetricsRegistry()
        tok = reg.counter("t_tokens_total")
        adm = reg.counter("t_admitted_total")
        eng, clock = self._tp(reg)
        for t in range(0, 70, 5):
            clock.t = float(t)
            tok.inc(500)                      # 100 tokens/s
            adm.inc()
            st = eng.sample()["tps"]
        assert st["kind"] == "throughput"
        assert st["burn"] == {"10s": 0.0, "60s": 0.0}
        assert not st["alert"]
        assert st["floor_per_s"] == 100.0
        assert st["rate_per_s"] == pytest.approx(100.0)
        assert st["budget_remaining"] == 1.0

    def test_idle_burns_zero(self):
        """No work AND no fresh demand = idle, not an outage."""
        reg = MetricsRegistry()
        reg.counter("t_tokens_total")
        reg.counter("t_admitted_total")
        eng, clock = self._tp(reg)
        for t in (0.0, 30.0, 120.0):
            clock.t = t
            st = eng.sample()["tps"]
        assert st["burn"] == {"10s": 0.0, "60s": 0.0}
        assert not st["alert"]

    def test_half_floor_burns_half_deficit_over_budget(self):
        reg = MetricsRegistry()
        tok = reg.counter("t_tokens_total")
        adm = reg.counter("t_admitted_total")
        eng, clock = self._tp(reg)
        for t in range(0, 15, 5):
            clock.t = float(t)
            tok.inc(250)                      # 50 tokens/s = half floor
            adm.inc()
            st = eng.sample()["tps"]
        # deficit 0.5 over budget 0.05 = burn 10
        assert st["burn"]["10s"] == pytest.approx(10.0, rel=0.05)

    def test_stall_under_demand_fires_and_clears(self):
        """The acceptance shape for tokens/s: decode stalls while
        admissions continue -> the alert fires within one fast window;
        tokens resume at the floor -> it clears."""
        reg = MetricsRegistry()
        tok = reg.counter("t_tokens_total")
        adm = reg.counter("t_admitted_total")
        eng, clock = self._tp(reg)
        for t in range(0, 60, 5):                  # healthy baseline
            clock.t = float(t)
            tok.inc(500)
            adm.inc()
            eng.sample()
        fired_at = None
        for t in range(60, 120, 2):                # stall, demand holds
            clock.t = float(t)
            adm.inc()
            if eng.sample()["tps"]["alert"] and fired_at is None:
                fired_at = t
        assert fired_at is not None
        assert fired_at - 60 <= WINDOWS[0].seconds + 2
        cleared_at = None
        for t in range(120, 200, 2):               # recovery at floor
            clock.t = float(t)
            tok.inc(200)                           # 100/s
            adm.inc()
            if not eng.sample()["tps"]["alert"] and cleared_at is None:
                cleared_at = t
        assert cleared_at is not None
        assert cleared_at - 120 <= WINDOWS[0].seconds + 2
        assert eng.state()["tps"]["alerts_total"] == 1


# -- alert listeners (ISSUE 17) ----------------------------------------------


class TestAlertListeners:
    def test_listener_fires_on_rising_edge_only(self):
        from deeplearning4j_tpu.observe import slo as slo_mod

        reg = MetricsRegistry()
        c = reg.counter("t_requests_total")
        eng, clock = _engine(reg)
        calls = []

        def listener(name, state):
            calls.append((name, state["alert"]))

        slo_mod.add_alert_listener(listener)
        try:
            for t in range(0, 60, 5):
                clock.t = float(t)
                c.inc(100, outcome="ok")
                eng.sample()
            assert calls == []
            for t in range(60, 120, 2):            # sustained errors
                clock.t = float(t)
                c.inc(100, outcome="error")
                eng.sample()
        finally:
            slo_mod.remove_alert_listener(listener)
        assert calls == [("avail", True)]          # one edge, one call
        # removed listeners stay silent on later edges
        for t in range(120, 180, 2):
            clock.t = float(t)
            c.inc(100, outcome="ok")
            eng.sample()
        assert len(calls) == 1

    def test_broken_listener_does_not_break_the_tick(self):
        from deeplearning4j_tpu.observe import slo as slo_mod

        reg = MetricsRegistry()
        c = reg.counter("t_requests_total")
        eng, clock = _engine(reg)

        def bad_listener(name, state):
            raise RuntimeError("boom")

        slo_mod.add_alert_listener(bad_listener)
        try:
            for t in range(0, 120, 5):
                clock.t = float(t)
                c.inc(100, outcome="error")
                st = eng.sample()                  # must not raise
        finally:
            slo_mod.remove_alert_listener(bad_listener)
        assert st["avail"]["alert"]


# -- exposition + lifecycle --------------------------------------------------


class TestExpositionAndLifecycle:
    def test_gauges_refresh_on_sample(self):
        reg = registry()
        c = reg.counter("dl4jtpu_serving_requests_total")
        clock = FakeClock()
        eng = SLOEngine(
            [SLObjective.availability("t_gauge_slo", target=0.99)],
            windows=WINDOWS, clock=clock,
        )
        c.inc(10, outcome="ok")
        eng.sample()
        clock.t = 5.0
        c.inc(90, outcome="error")
        st = eng.sample()["t_gauge_slo"]
        assert reg.gauge("dl4jtpu_slo_burn_rate").value(
            slo="t_gauge_slo", window="10s",
        ) == pytest.approx(st["burn"]["10s"])
        assert reg.gauge("dl4jtpu_slo_alert_active").value(
            slo="t_gauge_slo",
        ) == (1.0 if st["alert"] else 0.0)
        assert reg.counter("dl4jtpu_slo_alerts_total").value(
            slo="t_gauge_slo",
        ) == st["alerts_total"]

    def test_install_makes_every_scrape_an_evaluation_tick(self):
        reg = registry()
        eng = SLOEngine(
            [SLObjective.availability("t_install_slo", target=0.99)],
            windows=WINDOWS,
        )
        eng.install()
        try:
            assert active_engine() is eng
            # install() seeded a baseline sample...
            assert "t_install_slo" in eng.state()
            n0 = len(eng._samples["t_install_slo"])
            reg.to_prometheus_text()            # ...and a scrape ticks
            assert len(eng._samples["t_install_slo"]) == n0 + 1
        finally:
            eng.uninstall()
        assert active_engine() is None
        n = len(eng._samples["t_install_slo"])
        reg.to_prometheus_text()                # no longer ticking
        assert len(eng._samples["t_install_slo"]) == n

    def test_healthz_and_status_carry_slo_state(self):
        from deeplearning4j_tpu.models import SequentialModel
        from deeplearning4j_tpu.nn.conf import (
            Dense, InputType, NeuralNetConfiguration, OutputLayer,
        )
        from deeplearning4j_tpu.serving import (
            InferenceServer, ServingConfig, ServingHTTPServer,
        )

        conf = (
            NeuralNetConfiguration.builder().seed(7).list()
            .layer(Dense(n_out=8)).layer(OutputLayer(n_out=4))
            .set_input_type(InputType.feed_forward(6)).build()
        )
        srv = InferenceServer(SequentialModel(conf).init(),
                              ServingConfig(max_batch=4))
        http = ServingHTTPServer(srv, port=0).start()
        eng = SLOEngine(
            [SLObjective.availability("t_http_slo", target=0.99)],
            windows=WINDOWS,
        ).install()
        srv.start()
        try:
            eng.sample()
            srv.infer(np.zeros((6,), np.float32), deadline_s=10.0)
            with urllib.request.urlopen(http.url + "healthz") as r:
                health = json.loads(r.read())
            assert "slo" in health
            assert health["slo"]["alerting"] == []
            assert "t_http_slo" in health["slo"]["objectives"]
            with urllib.request.urlopen(http.url + "v1/status") as r:
                status = json.loads(r.read())
            assert "t_http_slo" in status["slo"]
            assert "latency_breakdown" in status
        finally:
            eng.uninstall()
            srv.stop()
            http.stop()

    def test_api_slo_endpoint_joins_local_and_workers(self):
        from deeplearning4j_tpu.observe import fleet as ofleet
        from deeplearning4j_tpu.ui.server import UIServer

        eng = SLOEngine(
            [SLObjective.availability("t_api_slo", target=0.99)],
            windows=WINDOWS,
        ).install()
        agg = ofleet.FleetAggregator()
        ofleet.set_active_aggregator(agg)
        ui = UIServer(port=0)
        try:
            eng.sample()
            agg.ingest("w0", {"rank": 0, "slo": {
                "avail": {"alert": True, "burn": {"300s": 20.0}},
            }})
            with urllib.request.urlopen(ui.url + "api/slo") as r:
                doc = json.loads(r.read())
            assert "t_api_slo" in doc["local"]
            assert doc["workers"]["w0"]["avail"]["alert"] is True
        finally:
            eng.uninstall()
            ofleet.clear_active_aggregator(agg)
            ui.stop()

    def test_fleet_push_payload_carries_slo_state(self):
        from deeplearning4j_tpu.observe.fleet import FleetReporter

        eng = SLOEngine(
            [SLObjective.availability("t_push_slo", target=0.99)],
            windows=WINDOWS,
        ).install()
        try:
            reporter = FleetReporter(client=None, rank=0)
            payload = reporter.payload()
            assert "t_push_slo" in payload["slo"]
        finally:
            eng.uninstall()


# -- meta-observability ------------------------------------------------------


class TestScrapeMeta:
    def test_scrape_times_itself_and_counts_series(self):
        reg = registry()
        reg.to_prometheus_text()        # the PREVIOUS scrape's timing...
        text = reg.to_prometheus_text()
        # ...is exposed on the next one
        line = [l for l in text.splitlines()
                if l.startswith("dl4jtpu_scrape_seconds ")]
        assert line and float(line[0].split()[-1]) > 0
        fams = reg.gauge("dl4jtpu_registry_families").value()
        series = reg.gauge("dl4jtpu_registry_series").value()
        assert fams > 50                 # the pre-declared core schema
        assert series >= fams            # histograms count their lines

    def test_bare_registry_stays_unpolluted(self):
        reg = MetricsRegistry()
        reg.counter("t_only_total").inc()
        reg.to_prometheus_text()
        text = reg.to_prometheus_text()
        assert "dl4jtpu_scrape_seconds" not in text
