"""Chunked large-vocab softmax cross-entropy: exact parity with the
dense loss in value and every gradient, at chunk sizes that don't
divide the vocab, with masks, and end-to-end through a DSL transformer."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.chunked_xent import chunked_softmax_xent

N, D, V = 12, 8, 37


def _dense_loss(h, W, b, ids, w):
    logits = h.astype(jnp.float32) @ W + b
    logp = jax.nn.log_softmax(logits, axis=-1)
    per = -jnp.take_along_axis(logp, ids[:, None], axis=-1)[:, 0]
    return jnp.sum(w * per) / jnp.maximum(jnp.sum(w), 1.0)


def _setup(seed=0):
    rng = np.random.default_rng(seed)
    h = jnp.asarray(rng.normal(0, 1, (N, D)).astype(np.float32))
    W = jnp.asarray(rng.normal(0, 0.5, (D, V)).astype(np.float32))
    b = jnp.asarray(rng.normal(0, 0.1, V).astype(np.float32))
    ids = jnp.asarray(rng.integers(0, V, N).astype(np.int32))
    return h, W, b, ids


@pytest.mark.parametrize("chunk", [8, 16, 37, 64])
def test_loss_value_matches_dense(chunk):
    h, W, b, ids = _setup()
    w = jnp.ones((N,), jnp.float32)
    got = chunked_softmax_xent(h, W, b, ids, w, chunk)
    want = _dense_loss(h, W, b, ids, w)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


@pytest.mark.parametrize("chunk", [8, 37, 64])
def test_gradients_match_dense(chunk):
    h, W, b, ids = _setup(1)
    w = jnp.ones((N,), jnp.float32)
    g_c = jax.grad(
        lambda h, W, b: chunked_softmax_xent(h, W, b, ids, w, chunk),
        argnums=(0, 1, 2),
    )(h, W, b)
    g_d = jax.grad(
        lambda h, W, b: _dense_loss(h, W, b, ids, w), argnums=(0, 1, 2)
    )(h, W, b)
    for a, e in zip(g_c, g_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-4, atol=1e-6)


def test_mask_weights_match_dense():
    h, W, b, ids = _setup(2)
    w = jnp.asarray((np.arange(N) % 3 != 0).astype(np.float32))
    got = chunked_softmax_xent(h, W, b, ids, w, 16)
    want = _dense_loss(h, W, b, ids, w)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    gc = jax.grad(lambda h: chunked_softmax_xent(h, W, b, ids, w, 16))(h)
    gd = jax.grad(lambda h: _dense_loss(h, W, b, ids, w))(h)
    np.testing.assert_allclose(np.asarray(gc), np.asarray(gd),
                               rtol=1e-4, atol=1e-6)
    # masked rows contribute zero gradient
    assert np.abs(np.asarray(gc)[::3]).max() < 1e-7


def test_bf16_hidden_states():
    h, W, b, ids = _setup(3)
    w = jnp.ones((N,), jnp.float32)
    got = chunked_softmax_xent(h.astype(jnp.bfloat16), W, b, ids, w, 16)
    want = _dense_loss(h.astype(jnp.bfloat16), W, b, ids, w)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-2)
    g = jax.grad(
        lambda hh: chunked_softmax_xent(hh, W, b, ids, w, 16)
    )(h.astype(jnp.bfloat16))
    assert g.dtype == jnp.bfloat16


def test_transformer_with_chunked_head_trains():
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.zoo.transformer import TransformerEncoder

    vocab = 50
    m = TransformerEncoder(
        vocab_size=vocab, d_model=16, n_heads=2, n_layers=1, causal=True,
        chunked_vocab_loss=True, vocab_chunk=16, learning_rate=5e-3,
    ).init_model()
    rng = np.random.default_rng(4)
    ids = rng.integers(0, vocab, (8, 12))
    x = ids.astype(np.float32)
    y = np.roll(ids, -1, axis=1).astype(np.float32)   # int next-token ids
    scores = []
    for _ in range(25):
        m.fit_batch(DataSet(x, y))
        scores.append(m.score_value)
    assert scores[-1] < scores[0] * 0.8, (scores[0], scores[-1])

    # parity with the dense head on the SAME initial params
    dense = TransformerEncoder(
        vocab_size=vocab, d_model=16, n_heads=2, n_layers=1, causal=True,
        seed=123, learning_rate=5e-3,
    ).init_model()
    chunked = TransformerEncoder(
        vocab_size=vocab, d_model=16, n_heads=2, n_layers=1, causal=True,
        seed=123, chunked_vocab_loss=True, vocab_chunk=16, learning_rate=5e-3,
    ).init_model()
    y_onehot = np.eye(vocab, dtype=np.float32)[np.roll(ids, -1, axis=1)]
    dense.fit_batch(DataSet(x, y_onehot))
    chunked.fit_batch(DataSet(x, y))
    np.testing.assert_allclose(dense.score_value, chunked.score_value,
                               rtol=1e-4)


def test_chunked_head_logits_for_inference():
    from deeplearning4j_tpu.nn.conf import ChunkedSoftmaxOutputLayer, InputType

    layer = ChunkedSoftmaxOutputLayer(n_out=V, chunk=16)
    params, _ = layer.init(jax.random.key(0), InputType.feed_forward(D))
    h = jnp.ones((2, D), jnp.float32)
    lg = layer.logits(params, h)
    assert lg.shape == (2, V)


def test_chunked_head_evaluate_uses_projected_logits():
    """evaluate() must project hidden states before argmax — raw apply()
    output is the d_model hidden, not class scores."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.zoo.transformer import TransformerEncoder

    vocab = 20
    m = TransformerEncoder(
        vocab_size=vocab, d_model=16, n_heads=2, n_layers=1, causal=True,
        chunked_vocab_loss=True, vocab_chunk=8, learning_rate=1e-2,
    ).init_model()
    rng = np.random.default_rng(6)
    ids = rng.integers(0, vocab, (8, 10))
    x = ids.astype(np.float32)
    y = np.roll(ids, -1, axis=1).astype(np.float32)
    for _ in range(60):
        m.fit_batch(DataSet(x, y))
    ev = m.evaluate(DataSet(x, y))
    assert ev.accuracy() > 0.5, ev.accuracy()
