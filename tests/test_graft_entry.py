"""The driver-facing entry points must stay green."""

import jax
import numpy as np


def test_entry_compiles_single_device():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 10)
    assert np.all(np.isfinite(np.asarray(out, dtype=np.float32)))


def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
