"""Tiny onnx.helper-equivalent for building test fixtures.

Builds real serialized ONNX ModelProto bytes via the vendored protobuf
codec — the same bytes `onnx.save` would produce for this schema subset —
so the importer is exercised end-to-end from wire format up.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.modelimport._onnx import onnx_subset_pb2 as pb

_NP_TO_ONNX = {
    np.dtype(np.float32): 1,
    np.dtype(np.int32): 6,
    np.dtype(np.int64): 7,
    np.dtype(np.bool_): 9,
    np.dtype(np.float64): 11,
}


def make_tensor(name: str, arr: np.ndarray) -> "pb.TensorProto":
    arr = np.asarray(arr)
    t = pb.TensorProto()
    t.name = name
    t.dims.extend(arr.shape)
    t.data_type = _NP_TO_ONNX[arr.dtype]
    t.raw_data = arr.astype(arr.dtype.newbyteorder("<")).tobytes()
    return t


def _set_attr(node, name, value):
    a = node.attribute.add()
    a.name = name
    if isinstance(value, bool):
        a.type, a.i = 2, int(value)
    elif isinstance(value, int):
        a.type, a.i = 2, value
    elif isinstance(value, float):
        a.type, a.f = 1, value
    elif isinstance(value, str):
        a.type, a.s = 3, value.encode()
    elif isinstance(value, (np.ndarray, np.generic)):
        a.type = 4
        a.t.CopyFrom(make_tensor(name, np.asarray(value)))
    elif isinstance(value, pb.GraphProto):
        a.type = 5
        a.g.CopyFrom(value)
    elif isinstance(value, (list, tuple)):
        if all(isinstance(v, int) for v in value):
            a.type = 7
            a.ints.extend(value)
        elif all(isinstance(v, float) for v in value):
            a.type = 6
            a.floats.extend(value)
        else:
            raise TypeError(f"mixed list attr {name}")
    else:
        raise TypeError(f"attr {name}: {type(value)}")


def make_node(op_type, inputs, outputs, name="", **attrs):
    n = pb.NodeProto()
    n.op_type = op_type
    n.input.extend(inputs)
    n.output.extend(outputs)
    n.name = name or f"{op_type}_{outputs[0]}"
    for k, v in attrs.items():
        _set_attr(n, k, v)
    return n


def make_graph(nodes, inputs, outputs, initializers=None,
               name="graph") -> "pb.GraphProto":
    """inputs/outputs: [(name, shape)] or [name]; initializers:
    {name: ndarray}.  Standalone GraphProto — also used for If/Loop
    subgraph attributes."""
    g = pb.GraphProto()
    g.name = name
    for n in nodes:
        g.node.add().CopyFrom(n)
    for iname, arr in (initializers or {}).items():
        g.initializer.add().CopyFrom(make_tensor(iname, np.asarray(arr)))
    for item in inputs:
        iname, shape = item if isinstance(item, tuple) else (item, ())
        vi = g.input.add()
        vi.name = iname
        vi.type.tensor_type.elem_type = 1
        for s in shape:
            d = vi.type.tensor_type.shape.dim.add()
            d.dim_value = s
    for item in outputs:
        oname = item if isinstance(item, str) else item[0]
        vi = g.output.add()
        vi.name = oname
        vi.type.tensor_type.elem_type = 1
    return g


def make_model(nodes, inputs, outputs, initializers=None,
               opset: int = 17) -> bytes:
    """inputs/outputs: [(name, shape)]; initializers: {name: ndarray}.
    Returns serialized ModelProto bytes."""
    m = pb.ModelProto()
    m.ir_version = 8
    op = m.opset_import.add()
    op.domain = ""
    op.version = opset
    m.graph.CopyFrom(make_graph(nodes, inputs, outputs, initializers,
                                name="test_graph"))
    return m.SerializeToString()
