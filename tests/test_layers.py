"""Per-layer forward-shape and semantics tests (the libnd4j layers_tests role)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    ActivationLayer,
    BatchNorm,
    Conv2D,
    Dense,
    Dropout,
    Embedding,
    GlobalPooling,
    InputType,
    LayerNorm,
    OutputLayer,
    PoolingType,
    Subsampling,
    Upsampling2D,
    ZeroPadding2D,
)
from deeplearning4j_tpu.nn.conf.layers import (
    Deconv2D,
    LocalResponseNormalization,
    SeparableConv2D,
)
from deeplearning4j_tpu.nn.weights import WeightInit

KEY = jax.random.key(0)


def run_layer(layer, itype, x, training=False, rng=None):
    params, state = layer.init(KEY, itype)
    y, new_state = layer.apply(params, state, jnp.asarray(x), training=training, rng=rng)
    expected = layer.output_type(itype)
    assert y.shape == (x.shape[0], *expected.shape), (
        f"{type(layer).__name__}: got {y.shape}, expected batch+{expected.shape}"
    )
    return y, params, new_state


def test_dense_shapes_and_linearity():
    layer = Dense(n_out=7, name="d", activation=Activation.IDENTITY)
    x = np.random.default_rng(0).normal(size=(4, 5)).astype(np.float32)
    y, params, _ = run_layer(layer, InputType.feed_forward(5), x)
    np.testing.assert_allclose(
        np.asarray(y), x @ np.asarray(params["W"]) + np.asarray(params["b"]), rtol=1e-5
    )


def test_dense_activation():
    layer = Dense(n_out=3, name="d", activation=Activation.RELU)
    x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    y, _, _ = run_layer(layer, InputType.feed_forward(4), x)
    assert np.all(np.asarray(y) >= 0)


@pytest.mark.parametrize("padding,expected_hw", [("valid", (24, 24)), ("same", (28, 28))])
def test_conv2d_shapes(padding, expected_hw):
    layer = Conv2D(n_out=6, kernel=(5, 5), padding=padding, name="c")
    itype = InputType.convolutional(28, 28, 1)
    out = layer.output_type(itype)
    assert out.shape == (*expected_hw, 6)
    x = np.random.default_rng(0).normal(size=(2, 28, 28, 1)).astype(np.float32)
    run_layer(layer, itype, x)


def test_conv2d_stride_dilation():
    layer = Conv2D(n_out=4, kernel=(3, 3), stride=(2, 2), dilation=(2, 2), name="c")
    itype = InputType.convolutional(16, 16, 3)
    x = np.random.default_rng(0).normal(size=(2, 16, 16, 3)).astype(np.float32)
    run_layer(layer, itype, x)


def test_conv2d_matches_manual_1x1():
    # 1x1 conv == per-pixel matmul
    layer = Conv2D(n_out=3, kernel=(1, 1), name="c", activation=Activation.IDENTITY)
    itype = InputType.convolutional(4, 4, 2)
    x = np.random.default_rng(0).normal(size=(2, 4, 4, 2)).astype(np.float32)
    y, params, _ = run_layer(layer, itype, x)
    w = np.asarray(params["W"])[0, 0]  # [in, out]
    manual = x @ w + np.asarray(params["b"])
    np.testing.assert_allclose(np.asarray(y), manual, rtol=1e-4, atol=1e-5)


def test_separable_and_deconv_shapes():
    it = InputType.convolutional(8, 8, 4)
    x = np.random.default_rng(0).normal(size=(2, 8, 8, 4)).astype(np.float32)
    run_layer(SeparableConv2D(n_out=6, kernel=(3, 3), name="s"), it, x)
    run_layer(Deconv2D(n_out=2, kernel=(2, 2), stride=(2, 2), name="d"), it, x)


def test_maxpool_values():
    layer = Subsampling(pooling=PoolingType.MAX, kernel=(2, 2), stride=(2, 2), name="p")
    x = np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1)
    y, _, _ = run_layer(layer, InputType.convolutional(4, 4, 1), x)
    np.testing.assert_array_equal(np.asarray(y)[0, :, :, 0], [[5, 7], [13, 15]])


def test_avgpool_values():
    layer = Subsampling(pooling=PoolingType.AVG, kernel=(2, 2), stride=(2, 2), name="p")
    x = np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1)
    y, _, _ = run_layer(layer, InputType.convolutional(4, 4, 1), x)
    np.testing.assert_allclose(np.asarray(y)[0, :, :, 0], [[2.5, 4.5], [10.5, 12.5]])


def test_batchnorm_train_and_infer():
    layer = BatchNorm(name="bn", decay=0.5)
    itype = InputType.feed_forward(3)
    x = np.random.default_rng(0).normal(loc=5.0, scale=2.0, size=(64, 3)).astype(np.float32)
    params, state = layer.init(KEY, itype)
    y, new_state = layer.apply(params, state, jnp.asarray(x), training=True, rng=None)
    # batch-normalized output ~ zero mean unit var
    np.testing.assert_allclose(np.asarray(y).mean(axis=0), 0.0, atol=1e-4)
    np.testing.assert_allclose(np.asarray(y).std(axis=0), 1.0, atol=1e-2)
    # running stats moved toward batch stats
    assert np.all(np.asarray(new_state["mean"]) != 0.0)
    # inference path uses running stats, returns same state
    y2, s2 = layer.apply(params, new_state, jnp.asarray(x), training=False, rng=None)
    assert s2 is new_state


def test_layernorm():
    layer = LayerNorm(name="ln")
    x = np.random.default_rng(0).normal(size=(4, 10)).astype(np.float32)
    y, _, _ = run_layer(layer, InputType.feed_forward(10), x)
    np.testing.assert_allclose(np.asarray(y).mean(axis=-1), 0.0, atol=1e-5)


def test_dropout_train_vs_infer():
    layer = Dropout(rate=0.5, name="do")
    x = np.ones((10, 100), np.float32)
    y_inf, _ = layer.apply({}, {}, jnp.asarray(x), training=False, rng=None)
    np.testing.assert_array_equal(np.asarray(y_inf), x)
    y_tr, _ = layer.apply({}, {}, jnp.asarray(x), training=True, rng=jax.random.key(1))
    arr = np.asarray(y_tr)
    assert np.any(arr == 0.0)
    assert abs(arr.mean() - 1.0) < 0.1  # inverted dropout preserves expectation


def test_embedding_ff_and_seq():
    layer = Embedding(n_in=50, n_out=8, name="e")
    params, _ = layer.init(KEY, InputType.feed_forward(50))
    ids = jnp.asarray([[1, 2, 3], [4, 5, 6]])
    y, _ = layer.apply(params, {}, ids, training=False, rng=None)
    assert y.shape == (2, 3, 8)
    np.testing.assert_array_equal(np.asarray(y[0, 0]), np.asarray(params["W"])[1])


def test_global_pooling_cnn():
    layer = GlobalPooling(pooling=PoolingType.AVG, name="gp")
    x = np.random.default_rng(0).normal(size=(2, 4, 4, 5)).astype(np.float32)
    y, _, _ = run_layer(layer, InputType.convolutional(4, 4, 5), x)
    np.testing.assert_allclose(np.asarray(y), x.mean(axis=(1, 2)), rtol=1e-5)


def test_zeropad_upsample_lrn_activation():
    it = InputType.convolutional(4, 4, 2)
    x = np.random.default_rng(0).normal(size=(2, 4, 4, 2)).astype(np.float32)
    run_layer(ZeroPadding2D(padding=(1, 1, 2, 2), name="zp"), it, x)
    run_layer(Upsampling2D(size=(2, 2), name="up"), it, x)
    run_layer(LocalResponseNormalization(name="lrn"), it, x)
    run_layer(ActivationLayer(activation=Activation.TANH, name="a"), it, x)


def test_weight_inits():
    key = jax.random.key(3)
    for wi in WeightInit:
        if wi in (WeightInit.IDENTITY,):
            w = wi.init(key, (6, 6))
            np.testing.assert_array_equal(np.asarray(w), np.eye(6))
            continue
        w = wi.init(key, (50, 60))
        assert w.shape == (50, 60)
        assert np.all(np.isfinite(np.asarray(w)))
    # he-normal std ~ sqrt(2/fan_in)
    w = WeightInit.RELU.init(key, (1000, 100))
    assert abs(np.asarray(w).std() - np.sqrt(2 / 1000)) < 0.005


def test_scale_shift_layer_and_serde():
    """ScaleShift: fixed x*scale+shift (ScaleVertex role as a layer) —
    the device-side normalizer for the uint8 ETL wire path."""
    from deeplearning4j_tpu.nn.conf import ScaleShift
    from deeplearning4j_tpu.utils import serde

    layer = ScaleShift(scale=1 / 255., shift=-0.5, name="s")
    x = np.arange(12, dtype=np.float32).reshape(3, 4) * 20
    y, params, _ = run_layer(layer, InputType.feed_forward(4), x)
    assert params == {}
    np.testing.assert_allclose(np.asarray(y), x / 255. - 0.5, atol=1e-6)
    clone = serde.from_jsonable(serde.to_jsonable(layer))
    assert clone == layer
