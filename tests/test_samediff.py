"""SameDiff-role autodiff graph tests."""

import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.nn import Adam


def test_forward_arithmetic():
    sd = SameDiff()
    x = sd.placeholder("x")
    w = sd.var("w", np.array([[2.0, 0.0], [0.0, 3.0]], np.float32))
    y = (x @ w) + 1.0
    out = np.asarray(sd.output({"x": np.eye(2, dtype=np.float32)}, y.name))
    np.testing.assert_allclose(out, [[3.0, 1.0], [1.0, 4.0]])


def test_grad_matches_analytic():
    sd = SameDiff()
    x = sd.placeholder("x")
    w = sd.var("w", np.array([1.0, 2.0, 3.0], np.float32))
    loss = ((x * w) ** 2.0).sum()
    sd.set_loss(loss)
    xval = np.array([1.0, 1.0, 2.0], np.float32)
    g = sd.grad({"x": xval})
    # d/dw sum((x*w)^2) = 2*x^2*w
    np.testing.assert_allclose(np.asarray(g["w"]), 2 * xval**2 * np.array([1, 2, 3]), rtol=1e-5)


def test_namespaces_and_eval():
    sd = SameDiff()
    x = sd.placeholder("x")
    h = sd.nn.relu(x, name="h")
    s = sd.nn.softmax(h, name="probs")
    out = np.asarray(sd.output({"x": np.array([[1.0, -1.0]], np.float32)}, "probs"))
    np.testing.assert_allclose(out.sum(axis=-1), 1.0, rtol=1e-5)
    assert out[0, 0] > out[0, 1]


def test_linear_regression_trains():
    rng = np.random.default_rng(0)
    true_w = np.array([[2.0], [-3.0]], np.float32)
    X = rng.normal(size=(256, 2)).astype(np.float32)
    Y = X @ true_w + 0.01 * rng.normal(size=(256, 1)).astype(np.float32)

    sd = SameDiff()
    x = sd.placeholder("x")
    y = sd.placeholder("y")
    w = sd.var("w", np.zeros((2, 1), np.float32))
    b = sd.var("b", np.zeros((1,), np.float32))
    pred = (x @ w) + b
    loss = sd.loss.mse_loss(pred, y, name="loss")
    sd.set_loss(loss)
    sd.set_training_config(TrainingConfig(updater=Adam(learning_rate=0.1)))
    for _ in range(200):
        sd.fit_batch({"x": X, "y": Y})
    np.testing.assert_allclose(sd.get_value("w"), true_w, atol=0.05)


def test_mlp_classification_trains():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(512, 2)).astype(np.float32)
    labels = (X[:, 0] * X[:, 1] > 0).astype(np.int64)
    Y = np.eye(2, dtype=np.float32)[labels]

    sd = SameDiff(seed=3)
    x = sd.placeholder("x")
    y = sd.placeholder("y")
    w1 = sd.var("w1", 0.5 * rng.normal(size=(2, 32)).astype(np.float32))
    b1 = sd.var("b1", np.zeros(32, np.float32))
    w2 = sd.var("w2", 0.5 * rng.normal(size=(32, 2)).astype(np.float32))
    b2 = sd.var("b2", np.zeros(2, np.float32))
    h = sd.nn.tanh((x @ w1) + b1)
    logits = sd.apply("add", sd.apply("matmul", h, w2), b2, name="logits")
    loss = sd.loss.softmax_cross_entropy(logits, y, name="loss")
    sd.set_training_config(TrainingConfig(updater=Adam(1e-2), loss_variable="loss"))
    for _ in range(300):
        sd.fit_batch({"x": X, "y": Y})
    pred = np.asarray(sd.output({"x": X}, "logits")).argmax(axis=1)
    assert (pred == labels).mean() > 0.95


def test_save_load_round_trip(tmp_path):
    sd = SameDiff()
    x = sd.placeholder("x")
    w = sd.var("w", np.array([[1.5]], np.float32))
    out = sd.nn.sigmoid(x @ w, name="out")
    p = str(tmp_path / "graph.sdz")
    sd.save(p)
    sd2 = SameDiff.load(p)
    xv = np.array([[2.0]], np.float32)
    np.testing.assert_allclose(
        np.asarray(sd.output({"x": xv}, "out")),
        np.asarray(sd2.output({"x": xv}, "out")),
    )


def test_save_load_resumes_training(tmp_path):
    rng = np.random.default_rng(2)
    X = rng.normal(size=(64, 3)).astype(np.float32)
    Y = (X @ np.array([[1.0], [2.0], [3.0]], np.float32))
    sd = SameDiff()
    x, y = sd.placeholder("x"), sd.placeholder("y")
    w = sd.var("w", np.zeros((3, 1), np.float32))
    loss = sd.loss.mse_loss(x @ w, y, name="loss")
    sd.set_training_config(TrainingConfig(updater=Adam(0.05), loss_variable="loss"))
    for _ in range(50):
        sd.fit_batch({"x": X, "y": Y})
    p = str(tmp_path / "g.sdz")
    sd.save(p)
    sd2 = SameDiff.load(p)
    # round 5: optimizer state persists — the resumed step is bit-for-bit
    # the step the un-serialized model would have taken (Adam moments
    # restored, not re-warmed)
    import jax

    assert sd2._opt_state is not None
    for a, b in zip(jax.tree.leaves(sd._opt_state),
                    jax.tree.leaves(sd2._opt_state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)
    want = sd.fit_batch({"x": X, "y": Y})
    l0 = sd2.fit_batch({"x": X, "y": Y})
    np.testing.assert_allclose(l0, want, rtol=1e-5)
    for _ in range(100):
        l1 = sd2.fit_batch({"x": X, "y": Y})
    assert l1 < l0


def test_save_load_resumes_rng_stream_for_dropout(tmp_path):
    """Resume parity must hold for STOCHASTIC graphs too: the checkpoint
    carries the SeedStream position, so the resumed step draws the same
    dropout mask the uninterrupted run would have (r5 review finding)."""
    rng = np.random.default_rng(5)
    X = rng.normal(size=(32, 6)).astype(np.float32)
    Y = rng.normal(size=(32, 1)).astype(np.float32)
    sd = SameDiff()
    x, y = sd.placeholder("x"), sd.placeholder("y")
    w = sd.var("w", rng.normal(size=(6, 1)).astype(np.float32) * 0.3)
    h = sd.apply("dropout", x @ w, rate=0.5, name="h")
    sd.loss.mse_loss(h, y, name="loss")
    sd.set_training_config(TrainingConfig(updater=Adam(0.01),
                                          loss_variable="loss"))
    for _ in range(5):
        sd.fit_batch({"x": X, "y": Y})
    p = str(tmp_path / "g.sdz")
    sd.save(p)
    sd2 = SameDiff.load(p)
    # identical key sequence -> identical masks -> identical next steps
    for _ in range(3):
        want = sd.fit_batch({"x": X, "y": Y})
        got = sd2.fit_batch({"x": X, "y": Y})
        np.testing.assert_allclose(got, want, rtol=1e-6)


def test_missing_placeholder_rejected():
    sd = SameDiff()
    x = sd.placeholder("x")
    y = sd.placeholder("y")
    z = x + y
    with pytest.raises(ValueError, match="missing placeholder"):
        sd.output({"x": np.ones(2, np.float32)}, z.name)


def test_duplicate_variable_rejected():
    sd = SameDiff()
    sd.var("w", np.zeros(2))
    with pytest.raises(ValueError, match="already exists"):
        sd.var("w", np.zeros(3))


def test_conv_graph():
    sd = SameDiff()
    x = sd.placeholder("x")
    k = sd.var("k", 0.1 * np.ones((3, 3, 1, 4), np.float32))
    c = sd.nn.conv2d(x, k, name="c", stride=(1, 1), padding="SAME")
    pooled = sd.nn.max_pool2d(c, name="p", kernel=(2, 2), stride=(2, 2))
    out = np.asarray(
        sd.output({"x": np.ones((2, 8, 8, 1), np.float32)}, "p")
    )
    assert out.shape == (2, 4, 4, 4)


def test_changing_training_config_recompiles():
    from deeplearning4j_tpu.nn import Sgd

    rng = np.random.default_rng(3)
    X = rng.normal(size=(32, 2)).astype(np.float32)
    Y = X @ np.array([[1.0], [1.0]], np.float32)
    sd = SameDiff()
    x, y = sd.placeholder("x"), sd.placeholder("y")
    w = sd.var("w", np.zeros((2, 1), np.float32))
    loss = sd.loss.mse_loss(x @ w, y, name="loss")
    sd.set_training_config(TrainingConfig(updater=Sgd(0.1), loss_variable="loss"))
    sd.fit_batch({"x": X, "y": Y})
    # switching updater must not reuse the cached Sgd step with Adam state
    sd.set_training_config(TrainingConfig(updater=Adam(0.05), loss_variable="loss"))
    l = sd.fit_batch({"x": X, "y": Y})
    assert np.isfinite(l)


def test_duplicate_op_name_leaves_graph_clean():
    sd = SameDiff()
    x = sd.placeholder("x")
    with pytest.raises(ValueError, match="already exists"):
        sd.apply("relu", x, name="x")
    # the failed apply must not leave a dangling node
    assert len(sd._ops) == 0
    out = sd.nn.relu(x, name="ok")
    np.testing.assert_allclose(
        np.asarray(sd.output({"x": -np.ones(2, np.float32)}, "ok")), 0.0
    )


def test_dropout_without_rate_infers_and_outputs():
    sd = SameDiff()
    x = sd.placeholder("x")
    h = sd.nn.dropout(x, name="h")
    out = np.asarray(sd.output({"x": np.ones((2, 4), np.float32)}, "h"))
    np.testing.assert_allclose(out, 1.0)  # inference identity


def test_fit_with_generator_trains_all_epochs():
    X = np.ones((8, 1), np.float32)
    Y = 2 * X
    sd = SameDiff()
    x, y = sd.placeholder("x"), sd.placeholder("y")
    w = sd.var("w", np.zeros((1, 1), np.float32))
    sd.loss.mse_loss(x @ w, y, name="loss")
    sd.set_training_config(TrainingConfig(updater=Adam(0.1), loss_variable="loss"))
    losses = sd.fit(({"x": X, "y": Y} for _ in range(3)), epochs=4)
    assert len(losses) == 12
