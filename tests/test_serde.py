"""Config JSON round-trip tests (MultiLayerConfiguration.toJson/fromJson role)."""

import numpy as np

from deeplearning4j_tpu.models import SequentialModel
from deeplearning4j_tpu.nn import Adam, Nesterovs
from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    BatchNorm,
    Conv2D,
    Dense,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
    SequentialConfiguration,
    Subsampling,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.schedules import CosineSchedule, StepSchedule
from deeplearning4j_tpu.nn.weights import WeightInit


def build_conf():
    return (
        NeuralNetConfiguration.builder()
        .seed(99)
        .updater(Adam(learning_rate=CosineSchedule(initial=1e-3, decay_steps=500)))
        .weight_init(WeightInit.RELU)
        .activation(Activation.RELU)
        .l2(1e-4)
        .list()
        .layer(Conv2D(n_out=8, kernel=(3, 3), padding="same"))
        .layer(Subsampling(kernel=(2, 2), stride=(2, 2)))
        .layer(BatchNorm())
        .layer(Dense(n_out=16))
        .layer(OutputLayer(n_out=4, loss=Loss.MCXENT, activation=Activation.SOFTMAX))
        .set_input_type(InputType.convolutional(8, 8, 3))
        .build()
    )


def test_json_round_trip_equality():
    conf = build_conf()
    s = conf.to_json()
    conf2 = SequentialConfiguration.from_json(s)
    assert conf == conf2  # frozen dataclasses: structural equality
    assert conf2.to_json() == s


def test_round_tripped_conf_builds_identical_model():
    conf = build_conf()
    conf2 = SequentialConfiguration.from_json(conf.to_json())
    m1 = SequentialModel(conf).init()
    m2 = SequentialModel(conf2).init()
    for lname in m1.params:
        for pname in m1.params[lname]:
            np.testing.assert_array_equal(
                np.asarray(m1.params[lname][pname]), np.asarray(m2.params[lname][pname])
            )


def test_schedule_serde():
    from deeplearning4j_tpu.utils import serde

    s = StepSchedule(initial=0.1, decay_rate=0.5, step=100)
    rt = serde.loads(serde.dumps(s))
    assert rt == s


def test_updater_serde_with_float_lr():
    from deeplearning4j_tpu.utils import serde

    u = Nesterovs(learning_rate=0.05, momentum=0.8)
    rt = serde.loads(serde.dumps(u))
    assert rt == u
