"""BertWordPieceTokenizer + BertIterator (the BERT fine-tune input
pipeline, BASELINE config 4's front end)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import BertIterator, BertWordPieceTokenizer

VOCAB = {t: i for i, t in enumerate([
    "[PAD]", "[UNK]", "[CLS]", "[SEP]",
    "the", "quick", "brown", "fox", "jump", "##ed", "##s", "over", "dog",
    "un", "##believ", "##able", ",", ".",
])}


@pytest.fixture
def tok():
    return BertWordPieceTokenizer(VOCAB)


def test_wordpiece_greedy_longest_match(tok):
    assert tok.tokenize("unbelievable") == ["un", "##believ", "##able"]
    assert tok.tokenize("jumped") == ["jump", "##ed"]
    assert tok.tokenize("jumps") == ["jump", "##s"]


def test_basic_tokenizer_punct_and_case(tok):
    assert tok.tokenize("The quick, brown FOX.") == [
        "the", "quick", ",", "brown", "fox", "."]


def test_unknown_word_maps_to_unk(tok):
    assert tok.tokenize("zebra") == ["[UNK]"]


def test_vocab_txt_round_trip(tmp_path, tok):
    path = tmp_path / "vocab.txt"
    ordered = sorted(VOCAB, key=VOCAB.get)
    path.write_text("\n".join(ordered) + "\n")
    tok2 = BertWordPieceTokenizer(str(path))
    assert tok2.vocab == VOCAB
    assert tok2.tokenize("unbelievable") == tok.tokenize("unbelievable")


def test_encode_special_tokens_and_padding(tok):
    ids, mask, seg = tok.encode("the fox", max_len=8)
    assert ids[0] == VOCAB["[CLS]"]
    assert ids[3] == VOCAB["[SEP]"]
    assert mask.tolist() == [1, 1, 1, 1, 0, 0, 0, 0]
    assert ids[4:].tolist() == [0, 0, 0, 0]          # [PAD]


def test_encode_pair_segments_and_truncation(tok):
    ids, mask, seg = tok.encode("the quick brown fox", "the dog", max_len=10)
    # [CLS] a... [SEP] b... [SEP]
    assert int(mask.sum()) <= 10
    sep = VOCAB["[SEP]"]
    sep_positions = [i for i, v in enumerate(ids.tolist()) if v == sep]
    assert len(sep_positions) == 2
    assert seg[sep_positions[0] + 1] == 1            # pair segment
    # longest-first truncation keeps both segments
    long_a = "the quick brown fox jumped over the dog " * 3
    ids2, mask2, seg2 = tok.encode(long_a, "the dog", max_len=12)
    assert int(mask2.sum()) == 12
    assert seg2.max() == 1


def test_bert_iterator_shapes_and_static_batches(tok):
    sents = ["the quick brown fox", "the dog", "unbelievable", "fox jumps",
             "the fox ."]
    it = BertIterator(tok, sents, [0, 1, 0, 1, 0], num_classes=2,
                      batch_size=2, max_len=12)
    batches = list(it)
    assert len(batches) == 3
    for b in batches:
        assert b.features.shape == (2, 12)           # static, tail padded
        assert b.features_mask.shape == (2, 12)
        assert b.labels.shape == (2, 2)
    # tail batch: second example masked out of the loss
    assert batches[-1].labels_mask.tolist() == [1.0, 0.0]


def test_bert_iterator_finetunes_a_transformer(tok):
    """End-to-end: WordPiece -> BertIterator -> DSL transformer classify."""
    from deeplearning4j_tpu.models import SequentialModel
    from deeplearning4j_tpu.nn import Adam
    from deeplearning4j_tpu.nn.activations import Activation
    from deeplearning4j_tpu.nn.conf import (
        Embedding, InputType, NeuralNetConfiguration, OutputLayer,
    )
    from deeplearning4j_tpu.nn.conf.attention import (
        PositionalEncoding, TransformerEncoderBlock,
    )
    from deeplearning4j_tpu.nn.conf.recurrent import LastTimeStep  # noqa: F401
    from deeplearning4j_tpu.nn.conf import GlobalPooling, PoolingType

    # separable toy task: class 0 sentences mention "fox", class 1 "dog"
    sents = (["the quick brown fox", "fox jumps over", "the fox ."] * 4
             + ["the dog", "over the dog .", "dog jumps"] * 4)
    labels = [0, 0, 0] * 4 + [1, 1, 1] * 4
    it = BertIterator(tok, sents, labels, num_classes=2, batch_size=8,
                      max_len=10)
    conf = (
        NeuralNetConfiguration.builder().seed(3).updater(Adam(5e-3))
        .list()
        .layer(Embedding(n_in=len(VOCAB), n_out=16))
        .layer(PositionalEncoding())
        .layer(TransformerEncoderBlock(d_model=16, n_heads=2))
        .layer(GlobalPooling(pooling=PoolingType.AVG))
        .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX))
        .set_input_type(InputType.recurrent(1, 10))
        .build()
    )
    m = SequentialModel(conf).init()
    m.fit(it, epochs=30)
    correct = 0
    for b in it:
        probs = np.asarray(m.output(b.features, b.features_mask))
        keep = b.labels_mask > 0
        correct += int((probs[keep].argmax(1) == b.labels[keep].argmax(1)).sum())
    assert correct / len(sents) > 0.9


def test_encode_max_len_too_small_raises(tok):
    with pytest.raises(ValueError, match="no room"):
        tok.encode("the", max_len=2)
    with pytest.raises(ValueError, match="no room"):
        tok.encode("the fox", "the dog", max_len=4)


def test_bert_iterator_caches_encoding(tok):
    it = BertIterator(tok, ["the fox", "the dog"], [0, 1], num_classes=2,
                      batch_size=2, max_len=8)
    list(it)
    cached = it._encoded
    assert cached is not None
    list(it)
    assert it._encoded is cached          # second epoch reused the cache
