"""ZeRO-1 sharded weight update (distribute(zero=1), parallel/zero.py).

The contract under test: reduce-scatter grads -> per-shard optimizer
update -> all-gather params is NUMERICALLY the replicated update — only
the layout of the update computation and the opt-state residency change.
Runs on the 8-device virtual CPU mesh the conftest configures.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.data import DataSet, NumpyDataSetIterator
from deeplearning4j_tpu.models import SequentialModel
from deeplearning4j_tpu.nn import Adam
from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    Dense,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.parallel import ParallelConfig, distribute
from deeplearning4j_tpu.parallel import zero as zmod
from deeplearning4j_tpu.runtime.mesh import DATA_AXIS
from deeplearning4j_tpu.train.listeners import TrainingListener

N_DEV = 8
IN = 8      # divisible by the mesh width -> first Dense W shards


def two_class_data(n=256, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, IN)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(axis=1) > 0).astype(int)]
    return x, y


def mlp_conf(seed=9):
    return (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Adam(1e-2))
        .activation(Activation.RELU)
        .list()
        .layer(Dense(n_out=32))
        .layer(Dense(n_out=32))
        .layer(OutputLayer(n_out=2, loss=Loss.MCXENT,
                           activation=Activation.SOFTMAX))
        .set_input_type(InputType.feed_forward(IN))
        .build()
    )


def params_allclose(a, b, rtol=2e-4, atol=2e-5):
    for lname in a:
        for pname in a[lname]:
            np.testing.assert_allclose(
                np.asarray(a[lname][pname]), np.asarray(b[lname][pname]),
                rtol=rtol, atol=atol, err_msg=f"{lname}/{pname}",
            )


def opt_specs(model):
    return {
        str(getattr(leaf, "sharding", None) and leaf.sharding.spec)
        for leaf in jax.tree.leaves(model.opt_state)
    }


# ---------------------------------------------------------------------------
class TestNumericsParity:
    def test_sharded_matches_replicated_across_fit_evaluate(self):
        """Same seed, same feed, interleaved fit/evaluate: the ZeRO-1
        param trajectory must match the replicated one within f32
        tolerance, and evaluate() (replicated params path) must agree."""
        x, y = two_class_data(256)
        it = lambda s: NumpyDataSetIterator(x, y, batch_size=64, seed=s)

        rep = SequentialModel(mlp_conf()).init()
        distribute(rep, ParallelConfig(data=N_DEV, zero=0))
        z = SequentialModel(mlp_conf()).init()
        distribute(z, ParallelConfig(data=N_DEV, zero=1))

        rep.fit(it(3), epochs=2)
        z.fit(it(3), epochs=2)
        params_allclose(rep.params, z.params)

        # an evaluate() between fits must not perturb either stream
        acc_rep = rep.evaluate(DataSet(x, y)).accuracy()
        acc_z = z.evaluate(DataSet(x, y)).accuracy()
        assert acc_rep == pytest.approx(acc_z, abs=0.02)

        rep.fit(it(5), epochs=1)
        z.fit(it(5), epochs=1)
        params_allclose(rep.params, z.params)

    def test_sharded_matches_single_device(self):
        """Transitively with test_parallel's DP parity: ZeRO-1 == pure
        DP == single device."""
        x, y = two_class_data(256)
        it = lambda s: NumpyDataSetIterator(x, y, batch_size=64, seed=s)
        single = SequentialModel(mlp_conf()).init()
        single.fit(it(3), epochs=3)
        z = SequentialModel(mlp_conf()).init()
        distribute(z, ParallelConfig(data=N_DEV, zero=1))
        z.fit(it(3), epochs=3)
        params_allclose(single.params, z.params)

    def test_graph_model_sharded_update(self):
        from deeplearning4j_tpu.models.computation_graph import GraphModel
        from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder

        def gconf():
            return (
                GraphBuilder()
                .updater(Adam(1e-2))
                .seed(9)
                .add_inputs("in")
                .set_input_types(InputType.feed_forward(IN))
                .add_layer("d", Dense(n_out=32), "in")
                .add_layer(
                    "out",
                    OutputLayer(n_out=2, loss=Loss.MCXENT,
                                activation=Activation.SOFTMAX),
                    "d",
                )
                .set_outputs("out")
                .build()
            )

        x, y = two_class_data(128)
        batches = [DataSet(x[i:i + 32], y[i:i + 32]) for i in range(0, 128, 32)]
        rep = GraphModel(gconf()).init()
        distribute(rep, ParallelConfig(data=N_DEV))
        z = GraphModel(gconf()).init()
        distribute(z, ParallelConfig(data=N_DEV, zero=1))
        for b in batches:
            rep.fit_batch(b)
            z.fit_batch(b)
        assert any(DATA_AXIS in s for s in opt_specs(z))
        for pk in rep.params:
            for pn in rep.params[pk]:
                np.testing.assert_allclose(
                    np.asarray(rep.params[pk][pn]),
                    np.asarray(z.params[pk][pn]),
                    rtol=2e-4, atol=2e-5,
                )


class TestPlacement:
    def test_opt_state_actually_sharded_and_params_replicated(self):
        z = SequentialModel(mlp_conf()).init()
        distribute(z, ParallelConfig(data=N_DEV, zero=1))
        specs = opt_specs(z)
        assert any(DATA_AXIS in s for s in specs), specs
        # params stay replicated (ZeRO-1, not ZeRO-3)
        for leaf in jax.tree.leaves(z.params):
            assert str(leaf.sharding.spec) == "PartitionSpec()"
        # the divisible leaves' per-replica bytes shrink 1/n
        rep = SequentialModel(mlp_conf()).init()
        distribute(rep, ParallelConfig(data=N_DEV))
        b_z = zmod.opt_state_bytes_per_replica(z.opt_state)
        b_rep = zmod.opt_state_bytes_per_replica(rep.opt_state)
        assert b_z < b_rep
        # stays sharded THROUGH training (donated buffers round-trip)
        x, y = two_class_data(128)
        z.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=1), epochs=1)
        assert any(DATA_AXIS in s for s in opt_specs(z))
        assert zmod.opt_state_bytes_per_replica(z.opt_state) == b_z

    def test_step_programs_registered_with_zero_marker(self):
        from deeplearning4j_tpu.observe import cost

        z = SequentialModel(mlp_conf()).init()
        distribute(z, ParallelConfig(data=N_DEV, zero=1))
        x, y = two_class_data(64)
        z.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=1), epochs=1)
        assert any("zero1" in k for k in z._step_fns)
        recs = [r for r in cost.registry().programs()
                if r.owner_ref() is z and r.kind.startswith("train")]
        assert recs and all("zero1" in str(r.key) for r in recs)

    def test_redistribute_without_zero_clears_placement(self):
        m = SequentialModel(mlp_conf()).init()
        distribute(m, ParallelConfig(data=N_DEV, zero=1))
        assert m._zero_placement is not None
        distribute(m, ParallelConfig(data=N_DEV))
        assert m._zero_placement is None
        for leaf in jax.tree.leaves(m.opt_state):
            assert str(leaf.sharding.spec) == "PartitionSpec()"

    def test_env_knob_enables_zero(self, monkeypatch):
        from deeplearning4j_tpu.runtime.flags import environment

        monkeypatch.setattr(environment(), "zero", 1)
        m = SequentialModel(mlp_conf()).init()
        distribute(m, ParallelConfig(data=N_DEV))        # zero=None -> env
        assert m._zero_placement is not None
        # explicit zero=0 overrides the env knob
        m2 = SequentialModel(mlp_conf()).init()
        distribute(m2, ParallelConfig(data=N_DEV, zero=0))
        assert m2._zero_placement is None

    def test_composition_errors(self):
        m = SequentialModel(mlp_conf()).init()
        with pytest.raises(ValueError, match="pure data parallelism"):
            distribute(m, ParallelConfig(data=2, model=4, zero=1))
        with pytest.raises(ValueError, match="pure data parallelism"):
            distribute(
                m, ParallelConfig(data=N_DEV, zero=1,
                                  grad_compression="int8"),
            )
        with pytest.raises(ValueError, match="zero stage"):
            distribute(m, ParallelConfig(data=N_DEV, zero=3))

    def test_spec_rule_prefers_largest_divisible_dim(self):
        from jax.sharding import PartitionSpec as P

        from deeplearning4j_tpu.parallel.strategy import zero1_spec_for_leaf

        a = np.zeros((5, 5, 1, 32), np.float32)     # conv HWIO: O shards
        assert zero1_spec_for_leaf(a, 8) == P(None, None, None, DATA_AXIS)
        b = np.zeros((16, 4), np.float32)
        assert zero1_spec_for_leaf(b, 8) == P(DATA_AXIS)
        c = np.zeros((2450, 500), np.float32)       # nothing divides 8
        assert zero1_spec_for_leaf(c, 8) == P()
        d = np.zeros((), np.float32)
        assert zero1_spec_for_leaf(d, 8) == P()


class TestCheckpointRoundTrip:
    def test_zip_checkpoint_save_restore_resume(self, tmp_path):
        """ModelSerializer path: save a ZeRO model, restore, re-place
        into a fresh distributed model, resume training — trajectory
        matches an uninterrupted run."""
        from deeplearning4j_tpu.train.checkpoint import ModelSerializer

        x, y = two_class_data(128)
        it = lambda s: NumpyDataSetIterator(x, y, batch_size=64, seed=s)

        z = SequentialModel(mlp_conf()).init()
        distribute(z, ParallelConfig(data=N_DEV, zero=1))
        z.fit(it(3), epochs=1)
        path = str(tmp_path / "zero.zip")
        ModelSerializer.write_model(z, path)

        restored = ModelSerializer.restore(path)
        distribute(restored, ParallelConfig(data=N_DEV, zero=1))
        assert any(DATA_AXIS in s for s in opt_specs(restored))
        for a, b in zip(jax.tree.leaves(z.opt_state),
                        jax.tree.leaves(restored.opt_state)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            )
        restored.fit(it(5), epochs=1)
        z.fit(it(5), epochs=1)
        params_allclose(z.params, restored.params)

    def test_orbax_sharded_checkpoint_gather_free_round_trip(self, tmp_path):
        """ShardedCheckpointer saves the ZeRO opt state PER SHARD and
        restores each leaf directly into its sharding — no host-side
        full-tree materialization, byte-exact round-trip, training
        resumes."""
        pytest.importorskip("orbax.checkpoint")
        from deeplearning4j_tpu.train.sharded_checkpoint import (
            ShardedCheckpointer,
        )

        x, y = two_class_data(128)
        z = SequentialModel(mlp_conf()).init()
        distribute(z, ParallelConfig(data=N_DEV, zero=1))
        z.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=3), epochs=1)

        ck = ShardedCheckpointer(str(tmp_path / "ck"), async_save=False)
        step = ck.save(z)
        ck.wait()

        m2 = SequentialModel(mlp_conf()).init()
        distribute(m2, ParallelConfig(data=N_DEV, zero=1))
        ck.restore_into(m2, step)
        assert any(DATA_AXIS in s for s in opt_specs(m2))
        for a, b in zip(jax.tree.leaves(z.opt_state),
                        jax.tree.leaves(m2.opt_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        m2.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=5), epochs=1)
        assert np.isfinite(m2.score_value)
        ck.close()


class TestShardAwareGuards:
    def test_listener_stashing_sharded_opt_state_trips_guard(self):
        class Stasher(TrainingListener):
            def iteration_done(self, model, iteration, epoch, score):
                self.stash = model.opt_state

        x, y = two_class_data(128)
        z = SequentialModel(mlp_conf()).init()
        distribute(z, ParallelConfig(data=N_DEV, zero=1))
        z.set_listeners(Stasher())
        with pytest.raises(RuntimeError, match="DONATES"):
            z.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=1),
                  epochs=1)

    def test_shard_view_alias_cannot_dodge_guard(self):
        """A listener stashing per-shard VIEWS (different Python
        objects, same device buffers) must still trip — buffer-pointer
        tracking, not id() tracking."""
        class ShardStasher(TrainingListener):
            def iteration_done(self, model, iteration, epoch, score):
                leaf = jax.tree.leaves(model.opt_state)[1]
                self.stash = [s.data for s in leaf.addressable_shards]

        x, y = two_class_data(128)
        z = SequentialModel(mlp_conf()).init()
        distribute(z, ParallelConfig(data=N_DEV, zero=1))
        z.set_listeners(ShardStasher())
        with pytest.raises(RuntimeError, match="DONATES"):
            z.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=1),
                  epochs=1)

    def test_copying_listener_passes(self):
        class Copier(TrainingListener):
            def iteration_done(self, model, iteration, epoch, score):
                self.snapshot = jax.tree.map(
                    lambda a: np.asarray(a), model.opt_state
                )

        x, y = two_class_data(128)
        z = SequentialModel(mlp_conf()).init()
        distribute(z, ParallelConfig(data=N_DEV, zero=1))
        z.set_listeners(Copier())
        z.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=1), epochs=1)
        assert z.iteration == 2


class TestRecoveryPlacement:
    def test_policy_attaches_to_single_process_distributed_model(self):
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        z = SequentialModel(mlp_conf()).init()
        distribute(z, ParallelConfig(data=N_DEV, zero=1))
        policy = RecoveryPolicy(store=None)
        policy.attach(z)           # must NOT raise on one process
        assert z._recovery is policy
        policy.detach(z)

    def test_install_replaces_restored_state_onto_shardings(self, tmp_path):
        """Rollback path: a checkpoint restored to host arrays must be
        re-placed onto the recorded shardings (replicated params,
        ZeRO-sharded opt state) — then training continues sharded."""
        from deeplearning4j_tpu.train.checkpoint import ModelSerializer
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        x, y = two_class_data(128)
        z = SequentialModel(mlp_conf()).init()
        distribute(z, ParallelConfig(data=N_DEV, zero=1))
        z.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=3), epochs=1)
        path = str(tmp_path / "ck.zip")
        ModelSerializer.write_model(z, path)

        restored = ModelSerializer.restore(path)     # host placement
        RecoveryPolicy._install(z, restored)
        assert any(DATA_AXIS in s for s in opt_specs(z))
        for leaf in jax.tree.leaves(z.params):
            assert str(leaf.sharding.spec) == "PartitionSpec()"
        z.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=5), epochs=1)
        assert np.isfinite(z.score_value)
        assert any(DATA_AXIS in s for s in opt_specs(z))


class TestAttribution:
    def test_opt_state_bytes_gauge_and_counter(self):
        from deeplearning4j_tpu.observe.metrics import registry

        z = SequentialModel(mlp_conf()).init()
        distribute(z, ParallelConfig(data=N_DEV, zero=1))
        g = registry().gauge("dl4jtpu_opt_state_bytes")
        assert g.value(mode="sharded") == zmod.opt_state_bytes_per_replica(
            z.opt_state
        )
        c = registry().counter("dl4jtpu_update_seconds_total")
        before = c.value(mode="sharded")
        secs = zmod.measure_update_seconds(z, iters=2)
        assert secs > 0
        assert c.value(mode="sharded") > before

    def test_update_seconds_measures_replicated_too(self):
        from deeplearning4j_tpu.observe.metrics import registry

        m = SequentialModel(mlp_conf()).init()
        distribute(m, ParallelConfig(data=N_DEV))
        c = registry().counter("dl4jtpu_update_seconds_total")
        before = c.value(mode="replicated")
        assert zmod.measure_update_seconds(m, iters=2) > 0
        assert c.value(mode="replicated") > before


class TestShardMapShim:
    """runtime/mesh.py's jax.shard_map compatibility shim (the 31
    tier-1 un-failures ride on it)."""

    def test_psum_and_axis_size(self):
        from jax.sharding import PartitionSpec as P

        from deeplearning4j_tpu.runtime.mesh import (
            MeshSpec, axis_size, make_mesh, shard_map,
        )

        mesh = make_mesh(MeshSpec.data_parallel())
        f = shard_map(
            lambda x: jax.lax.psum(x, DATA_AXIS) * 0 + axis_size(DATA_AXIS),
            mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P(DATA_AXIS),
            check_vma=False,
        )
        out = np.asarray(jax.jit(f)(jnp.arange(float(N_DEV))))
        np.testing.assert_array_equal(out, np.full(N_DEV, N_DEV))

    def test_size_one_auto_axes_fold_into_manual(self):
        from jax.sharding import PartitionSpec as P

        from deeplearning4j_tpu.runtime.mesh import MeshSpec, make_mesh, shard_map

        mesh = make_mesh(
            MeshSpec.of(data=1, pipe=4), jax.devices()[:4]
        )
        f = shard_map(
            lambda x: x * 2, mesh=mesh, in_specs=(P("pipe"),),
            out_specs=P("pipe"), axis_names={"pipe"}, check_vma=False,
        )
        np.testing.assert_array_equal(
            np.asarray(jax.jit(f)(jnp.arange(4.0))), np.arange(4.0) * 2
        )

    def test_legacy_partial_auto_raises_actionably(self):
        from jax.sharding import PartitionSpec as P

        from deeplearning4j_tpu.runtime.mesh import MeshSpec, make_mesh, shard_map

        if hasattr(jax, "shard_map"):
            pytest.skip("native partial-auto shard_map available")
        mesh = make_mesh(MeshSpec.of(data=2, pipe=4))
        with pytest.raises(NotImplementedError, match="auto"):
            shard_map(
                lambda x: x, mesh=mesh, in_specs=(P("pipe"),),
                out_specs=P("pipe"), axis_names={"pipe"},
                check_vma=False,
            )
