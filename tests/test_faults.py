"""Fault-injection harness + control-plane retry + verified checkpoints
(ISSUE 3): every ugly failure here is provoked DETERMINISTICALLY through
`runtime.faults`, and the stack must absorb it — retries recover dropped
rpcs, verification catches truncated checkpoints, `CheckpointStore` falls
back to the last GOOD file, and a SIGKILL mid-write never corrupts the
published state.

All tests carry the `faults` marker (`pytest -m faults`) and run inside
tier-1: backoff clocks are injected so patient retry budgets never
wall-clock, and no sleep exceeds 0.5 s.
"""

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.runtime import faults
from deeplearning4j_tpu.runtime.coordinator import (
    CoordinatorClient,
    CoordinatorServer,
    RetryExhausted,
    RetryPolicy,
    default_retry_policies,
)

pytestmark = pytest.mark.faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "elastic_worker.py")


@pytest.fixture(autouse=True)
def _disarm():
    """Never leak an armed plan into the next test."""
    yield
    faults.disarm()


def _model():
    from deeplearning4j_tpu.models import SequentialModel
    from deeplearning4j_tpu.nn.conf import (
        Dense, InputType, NeuralNetConfiguration, OutputLayer,
    )

    conf = (
        NeuralNetConfiguration.builder().seed(3).list()
        .layer(Dense(n_out=8)).layer(OutputLayer(n_out=2))
        .set_input_type(InputType.feed_forward(4)).build()
    )
    return SequentialModel(conf).init()


# -- the harness itself -----------------------------------------------------

class TestFaultPlan:
    def test_grammar_parse_and_spec_roundtrip(self):
        text = ("coordinator.rpc:raise:every=3;"
                "checkpoint.write:truncate:nth=2;"
                "heartbeat.send:delay:every=2,secs=0.01;"
                "data.next_batch:raise:p=0.5,seed=3,max=2")
        plan = faults.FaultPlan.parse(text)
        assert plan.sites() == ["checkpoint.write", "coordinator.rpc",
                                "data.next_batch", "heartbeat.send"]
        # spec() -> parse() is a fixed point (the env-inheritance path)
        assert faults.FaultPlan.parse(plan.spec()).spec() == plan.spec()

    @pytest.mark.parametrize("bad", [
        "justasite", "s:unknownkind", "s:raise:bogus=1",
        "s:raise:nth=2,every=3", "", "s:raise:exc=nosuch",
    ])
    def test_grammar_rejects(self, bad):
        with pytest.raises(ValueError):
            faults.FaultPlan.parse(bad)

    def test_every_and_nth_triggers(self):
        plan = faults.arm("a:raise:every=3;b:raise:nth=2")
        hits = []
        for i in range(1, 10):
            try:
                faults.maybe_fail("a")
                hits.append(0)
            except faults.InjectedFault:
                hits.append(1)
        assert hits == [0, 0, 1, 0, 0, 1, 0, 0, 1]
        assert faults.maybe_fail("b") is None
        with pytest.raises(faults.InjectedFault):
            faults.maybe_fail("b")
        assert faults.maybe_fail("b") is None      # nth is one-shot
        assert plan.stats()["a"] == {"consults": 9, "fires": 3}

    def test_probability_trigger_is_seeded_and_capped(self):
        def run():
            faults.arm("s:raise:p=0.5,seed=11,max=3")
            out = []
            for _ in range(20):
                try:
                    faults.maybe_fail("s")
                    out.append(0)
                except faults.InjectedFault:
                    out.append(1)
            return out

        a, b = run(), run()
        assert a == b                               # same seed, same trace
        assert sum(a) == 3                          # max= cap respected

    def test_delay_and_exc_variants(self):
        faults.arm("s:delay:nth=1,secs=0.05;t:raise:nth=1,exc=runtime")
        t0 = time.perf_counter()
        assert faults.maybe_fail("s") is None
        assert time.perf_counter() - t0 >= 0.04
        with pytest.raises(faults.InjectedError):
            faults.maybe_fail("t")
        # runtime-exc faults are NOT retryable by policy design
        assert not isinstance(faults.InjectedError("x"),
                              RetryPolicy.RETRYABLE)

    def test_disarmed_is_free(self):
        faults.disarm()
        assert not faults.is_armed()
        # acceptance: one global load + None check per site.  100k calls
        # comfortably under half a second even on a loaded CI box.
        t0 = time.perf_counter()
        for _ in range(100_000):
            faults.maybe_fail("coordinator.rpc")
        assert time.perf_counter() - t0 < 0.5

    def test_armed_unknown_site_is_noop(self):
        faults.arm("other:raise:every=1")
        assert faults.maybe_fail("not.in.plan") is None

    def test_env_inheritance_arms_at_import(self, tmp_path):
        """Subprocess workers inherit the plan via DL4J_TPU_FAULT_PLAN —
        armed at module import, before any site is consulted."""
        prog = (
            "import importlib.util, json, sys\n"
            f"spec = importlib.util.spec_from_file_location('f', "
            f"{os.path.join(REPO, 'deeplearning4j_tpu', 'runtime', 'faults.py')!r})\n"
            "m = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(m)\n"
            "assert m.is_armed()\n"
            "try:\n"
            "    m.maybe_fail('x.y')\n"
            "    raise SystemExit('no fault fired')\n"
            "except m.InjectedFault:\n"
            "    print('FIRED')\n"
        )
        env = dict(os.environ, DL4J_TPU_FAULT_PLAN="x.y:raise:nth=1")
        out = subprocess.run([sys.executable, "-c", prog], env=env,
                             capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        assert "FIRED" in out.stdout

    def test_fires_land_on_metrics_spine(self):
        from deeplearning4j_tpu.observe.metrics import registry

        c = registry().counter("dl4jtpu_faults_injected_total")
        before = c.value(site="spine.test")
        faults.arm("spine.test:raise:every=1")
        with pytest.raises(faults.InjectedFault):
            faults.maybe_fail("spine.test")
        assert c.value(site="spine.test") == before + 1


# -- retry / backoff --------------------------------------------------------

class TestRetryPolicy:
    def test_backoff_is_capped_exponential_with_jitter_bounds(self):
        up = RetryPolicy(max_attempts=9, base_delay=0.1, max_delay=1.0,
                         jitter=0.25, rand=lambda: 1.0)   # +jitter extreme
        down = RetryPolicy(max_attempts=9, base_delay=0.1, max_delay=1.0,
                           jitter=0.25, rand=lambda: 0.0)  # -jitter extreme
        assert up.backoff(2) == pytest.approx(0.1 * 1.25)
        assert down.backoff(2) == pytest.approx(0.1 * 0.75)
        assert up.backoff(3) == pytest.approx(0.2 * 1.25)
        # capped: attempt 9 raw would be 0.1 * 2^7 = 12.8
        assert up.backoff(9) == pytest.approx(1.0 * 1.25)

    def test_run_retries_transient_then_succeeds(self):
        slept = []
        p = RetryPolicy(max_attempts=5, base_delay=0.01, sleep=slept.append)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionResetError("transient")
            return "ok"

        assert p.run("op", flaky) == "ok"
        assert calls["n"] == 3 and len(slept) == 2

    def test_run_exhausts_into_retry_exhausted(self):
        p = RetryPolicy(max_attempts=3, sleep=lambda s: None)

        def always():
            raise ConnectionRefusedError("down")

        with pytest.raises(RetryExhausted) as ei:
            p.run("register", always)
        assert ei.value.op == "register" and ei.value.attempts == 3
        assert isinstance(ei.value.last, ConnectionRefusedError)

    def test_non_retryable_propagates_immediately(self):
        p = RetryPolicy(max_attempts=5, sleep=lambda s: None)
        calls = {"n": 0}

        def fatal():
            calls["n"] += 1
            raise RuntimeError("logic bug, not weather")

        with pytest.raises(RuntimeError):
            p.run("op", fatal)
        assert calls["n"] == 1

    def test_per_op_budgets(self):
        pol = default_retry_policies(sleep=lambda s: None)
        assert pol["register"].max_attempts > pol["report_ckpt"].max_attempts
        assert pol["heartbeat"].max_attempts == 1
        assert "*" in pol


class TestClientRetries:
    def test_dropped_rpcs_are_retried_transparently(self):
        srv = CoordinatorServer(expected_workers=1, heartbeat_timeout=30).start()
        try:
            faults.arm("coordinator.rpc:raise:every=2")   # drop every 2nd
            c = CoordinatorClient(
                srv.address, "w0",
                retry=default_retry_policies(sleep=lambda s: None),
            )
            reg = c.register()
            assert reg["rank"] == 0
            c.report_ckpt(3, "/tmp/x.zip")
            assert c.latest_ckpt()["step"] == 3
            faults.disarm()
            from deeplearning4j_tpu.observe.metrics import registry

            retries = registry().counter("dl4jtpu_rpc_retries_total")
            assert sum(
                retries.value(op=op)
                for op in ("register", "report_ckpt", "latest_ckpt")
            ) >= 1
        finally:
            faults.disarm()
            srv.stop()

    def test_register_is_idempotent_after_lost_response(self):
        """A sealed worker whose register() response got lost re-registers
        and gets its EXISTING assignment back — no ghost in the next
        barrier."""
        srv = CoordinatorServer(expected_workers=1, heartbeat_timeout=30).start()
        try:
            c = CoordinatorClient(srv.address, "w0")
            r1 = c.register()
            r2 = c.register()                     # the retry of a lost reply
            assert (r1["generation"], r1["rank"]) == (r2["generation"], r2["rank"])
            assert srv.generation == 1            # no second seal
        finally:
            srv.stop()

    def test_heartbeat_is_single_try(self):
        srv = CoordinatorServer(expected_workers=1, heartbeat_timeout=30).start()
        try:
            c = CoordinatorClient(srv.address, "w0")
            c.register()
            faults.arm("heartbeat.send:raise:nth=1")
            with pytest.raises(ConnectionError):
                c.heartbeat()                     # no retry: propagates
            faults.disarm()
            assert c.heartbeat()["ok"]            # next beat recovers
        finally:
            faults.disarm()
            srv.stop()

    def test_retry_exhausted_when_coordinator_gone(self):
        srv = CoordinatorServer(expected_workers=1, heartbeat_timeout=30).start()
        addr = srv.address
        srv.stop()                                # nobody listening now
        c = CoordinatorClient(
            addr, "w0", timeout=1.0,
            retry={"*": RetryPolicy(max_attempts=2, sleep=lambda s: None),
                   "register": RetryPolicy(max_attempts=2,
                                           sleep=lambda s: None)},
        )
        with pytest.raises(RetryExhausted):
            c.status()


class TestServerHardening:
    def test_half_open_client_does_not_pin_handler(self):
        """A client that connects and sends NOTHING (killed mid-request)
        must not wedge the server: the read times out, the handler thread
        is freed, and other clients keep getting answered."""
        import socket as _socket

        srv = CoordinatorServer(expected_workers=1, heartbeat_timeout=30,
                                request_timeout=0.3).start()
        try:
            host, port = srv.address.rsplit(":", 1)
            half_open = _socket.create_connection((host, int(port)))
            c = CoordinatorClient(srv.address, "w0")
            c.register()
            time.sleep(0.5)                       # past the read timeout
            assert c.status()["ok"]               # server still live
            # the half-open connection was dropped server-side
            half_open.settimeout(0.5)
            assert half_open.recv(1) == b""       # server closed it
            half_open.close()
        finally:
            srv.stop()

    def test_ledgers_are_bounded_rings(self):
        srv = CoordinatorServer(expected_workers=1, heartbeat_timeout=30).start()
        try:
            c = CoordinatorClient(srv.address, "w0")
            c.register()
            for i in range(CoordinatorServer.LEDGER_CAP + 44):
                c.report_ckpt(i, f"/tmp/{i}.zip")
            assert len(srv.history) == CoordinatorServer.LEDGER_CAP
            # latest wins even though the ring dropped the oldest entries
            assert c.latest_ckpt()["step"] == CoordinatorServer.LEDGER_CAP + 43
            assert srv.evictions.maxlen == CoordinatorServer.LEDGER_CAP
        finally:
            srv.stop()

    def test_generation_port_is_reserved_until_seal(self):
        """The jax_coordinator port is held (bound + listening) from server
        start until the seal hands it out — the close-then-reuse window is
        the worker's bring-up, not the whole registration barrier."""
        srv = CoordinatorServer(expected_workers=1, heartbeat_timeout=30).start()
        try:
            held = srv._port_hold.getsockname()[1]
            CoordinatorClient(srv.address, "w0").register()
            sealed_port = int(srv.jax_coordinator.rsplit(":", 1)[1])
            assert sealed_port == held             # the reservation was used
            # and a fresh reservation is already held for the next seal
            assert srv._port_hold is not None
            assert srv._port_hold.getsockname()[1] != sealed_port
        finally:
            srv.stop()


# -- checkpoint integrity + last-good fallback ------------------------------

class TestCheckpointIntegrity:
    def test_manifest_written_and_verify_passes(self, tmp_path):
        from deeplearning4j_tpu.train.checkpoint import (
            MANIFEST_NAME, ModelSerializer,
        )
        import zipfile

        m = _model()
        path = str(tmp_path / "m.zip")
        ModelSerializer.write_model(m, path)
        assert not os.path.exists(path + ".tmp")   # tmp consumed by publish
        with zipfile.ZipFile(path) as zf:
            manifest = json.loads(zf.read(MANIFEST_NAME))
        assert set(manifest["entries"]) >= {
            "configuration.json", "params.npz", "netstate.npz", "meta.json",
        }
        assert manifest["leaf_counts"]["params.npz"] == 4   # 2 layers x W,b
        meta = ModelSerializer.verify(path)
        assert meta["iteration"] == 0

    def test_verify_catches_truncation_and_bitflip(self, tmp_path):
        from deeplearning4j_tpu.train.checkpoint import (
            CheckpointVerifyError, ModelSerializer,
        )

        m = _model()
        good = tmp_path / "good.zip"
        ModelSerializer.write_model(m, str(good))
        raw = good.read_bytes()

        truncated = tmp_path / "trunc.zip"
        truncated.write_bytes(raw[: len(raw) // 2])
        with pytest.raises(CheckpointVerifyError):
            ModelSerializer.verify(str(truncated))

        flipped = tmp_path / "flip.zip"
        # flip one byte INSIDE an entry's compressed payload (past the
        # local header) — zip structure survives, CRC must not
        b = bytearray(raw)
        b[200] ^= 0xFF
        flipped.write_bytes(bytes(b))
        with pytest.raises(CheckpointVerifyError):
            ModelSerializer.verify(str(flipped))

        with pytest.raises(CheckpointVerifyError):
            ModelSerializer.verify(str(tmp_path / "missing.zip"))

    def test_restore_verifies_by_default(self, tmp_path):
        from deeplearning4j_tpu.train.checkpoint import (
            CheckpointVerifyError, ModelSerializer,
        )

        m = _model()
        path = tmp_path / "m.zip"
        ModelSerializer.write_model(m, str(path))
        path.write_bytes(path.read_bytes()[:-40])  # lop off the tail
        with pytest.raises(CheckpointVerifyError):
            ModelSerializer.restore(str(path))

    def test_pre_manifest_checkpoints_still_verify_and_restore(self, tmp_path):
        """v1 files (no manifest.json) fall back to the zip's own CRCs."""
        from deeplearning4j_tpu.train.checkpoint import (
            MANIFEST_NAME, ModelSerializer,
        )
        import zipfile

        m = _model()
        v2 = str(tmp_path / "v2.zip")
        ModelSerializer.write_model(m, v2)
        v1 = str(tmp_path / "v1.zip")
        with zipfile.ZipFile(v2) as zin, zipfile.ZipFile(v1, "w") as zout:
            for name in zin.namelist():
                if name != MANIFEST_NAME:
                    zout.writestr(name, zin.read(name))
        ModelSerializer.verify(v1)
        m2 = ModelSerializer.restore(v1)
        np.testing.assert_array_equal(
            np.asarray(m2.params["layer0"]["W"]),
            np.asarray(m.params["layer0"]["W"]),
        )

    def test_injected_truncate_fault_is_caught_by_store(self, tmp_path):
        from deeplearning4j_tpu.observe.metrics import registry
        from deeplearning4j_tpu.train.checkpoint import CheckpointStore

        store = CheckpointStore(str(tmp_path), keep_last=3)
        m = _model()
        m.iteration = 1
        store.save(m)
        faults.arm("checkpoint.write:truncate:nth=1")
        m.iteration = 2
        store.save(m)                              # publishes corrupt bytes
        faults.disarm()
        before = registry().counter(
            "dl4jtpu_ckpt_verify_failures_total").value(reason="corrupt")
        entry = store.latest_valid()
        assert entry["step"] == 1                  # last GOOD, not newest
        assert registry().counter(
            "dl4jtpu_ckpt_verify_failures_total"
        ).value(reason="corrupt") > before
        restored = store.restore_latest()
        assert restored.iteration == 1


class TestCheckpointStore:
    def test_gc_keeps_last_and_sweeps_tmp_orphans(self, tmp_path):
        from deeplearning4j_tpu.train.checkpoint import CheckpointStore

        store = CheckpointStore(str(tmp_path), keep_last=2)
        m = _model()
        for step in (1, 2, 3, 4):
            m.iteration = step
            store.save(m)
        assert store.all_steps() == [3, 4]
        with open(store.path_for(9) + ".tmp", "wb") as f:
            f.write(b"torn half-write")
        store.gc()
        assert not any(
            n.endswith(".tmp") for n in os.listdir(str(tmp_path))
        )

    def test_empty_and_missing_dir(self, tmp_path):
        from deeplearning4j_tpu.train.checkpoint import CheckpointStore

        store = CheckpointStore(str(tmp_path / "nope"))
        assert store.latest_valid() is None
        assert store.restore_latest() is None
        assert store.all_steps() == []
        store.gc()                                 # no-op, no raise

    def test_duck_types_preemption_checkpointer(self, tmp_path):
        from deeplearning4j_tpu.train.checkpoint import CheckpointStore
        from deeplearning4j_tpu.train.preemption import (
            PreemptionError, PreemptionHandler,
        )
        from deeplearning4j_tpu.data import DataSet

        store = CheckpointStore(str(tmp_path), keep_last=2)
        m = _model()
        handler = PreemptionHandler(store)
        m.set_listeners(handler.listener())
        handler.trigger()
        rng = np.random.default_rng(0)
        ds = DataSet(rng.normal(0, 1, (32, 4)).astype(np.float32),
                     np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)])
        with pytest.raises(PreemptionError):
            m.fit(ds, epochs=2, batch_size=16)
        handler.uninstall()
        steps = store.all_steps()
        assert steps, "no preemption checkpoint written"
        restored = store.restore_latest()
        assert restored.iteration == steps[-1]

    def test_kill_during_write_leaves_last_good_restorable(self, tmp_path):
        """THE kill -9 mid-checkpoint test: a subprocess SIGKILLs itself at
        the checkpoint.fsync site (after the zip bytes land in the .tmp,
        before the atomic publish) on its SECOND save, with the bytes also
        truncated — the torn .tmp must be ignored, the previous checkpoint
        restored, and gc() must sweep the orphan."""
        ckpt_dir = str(tmp_path / "ckpts")
        prog = (
            "import sys\n"
            f"sys.path.insert(0, {REPO!r}); sys.path.insert(0, {os.path.join(REPO, 'tests')!r})\n"
            "from elastic_worker import build_model\n"
            "from deeplearning4j_tpu.train.checkpoint import CheckpointStore\n"
            f"store = CheckpointStore({ckpt_dir!r}, keep_last=5)\n"
            "m = build_model()\n"
            "m.iteration = 1; store.save(m)\n"
            "print('SAVED1', flush=True)\n"
            "m.iteration = 2; store.save(m)\n"      # SIGKILL fires in here
            "print('UNREACHABLE', flush=True)\n"
        )
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update(
            JAX_PLATFORMS="cpu",
            DL4J_TPU_FAULT_PLAN=(
                "checkpoint.write:truncate:nth=2;checkpoint.fsync:kill:nth=2"
            ),
        )
        out = subprocess.run([sys.executable, "-c", prog], env=env, cwd=REPO,
                             capture_output=True, text=True, timeout=180)
        assert out.returncode == -signal.SIGKILL, (out.returncode, out.stderr)
        assert "SAVED1" in out.stdout
        assert "UNREACHABLE" not in out.stdout

        from deeplearning4j_tpu.train.checkpoint import CheckpointStore

        # the torn write is visible only as a .tmp orphan
        names = sorted(os.listdir(ckpt_dir))
        assert "ckpt_00000001.zip" in names
        assert "ckpt_00000002.zip" not in names     # never published
        assert any(n.endswith(".tmp") for n in names), names

        store = CheckpointStore(ckpt_dir, keep_last=5)
        entry = store.latest_valid()
        assert entry["step"] == 1                   # last good wins
        restored = store.restore_latest()
        assert restored.iteration == 1
        store.gc()
        assert not any(
            n.endswith(".tmp") for n in os.listdir(ckpt_dir)
        )


# -- preemption satellites --------------------------------------------------

class TestPreemptionHandlerHardening:
    def test_install_off_main_thread_raises_clear_error(self):
        from deeplearning4j_tpu.train.preemption import PreemptionHandler

        h = PreemptionHandler(signals=(signal.SIGUSR2,))
        caught = []

        def worker():
            try:
                h.install()
            except BaseException as e:
                caught.append(e)

        t = threading.Thread(target=worker)
        t.start()
        t.join(timeout=10)
        assert caught and isinstance(caught[0], RuntimeError)
        assert "main thread" in str(caught[0])
        assert not h._installed

    def test_uninstall_is_idempotent_incl_from_on_fit_end(self):
        from deeplearning4j_tpu.data import DataSet
        from deeplearning4j_tpu.train.preemption import (
            PreemptionHandler, PreemptionListener,
        )

        prev = signal.getsignal(signal.SIGUSR2)
        h = PreemptionHandler(signals=(signal.SIGUSR2,),
                              raise_after_save=False)

        class CleanupListener(PreemptionListener):
            def on_fit_end(self, model):
                self.handler.uninstall()           # listener-side cleanup

        m = _model()
        m.set_listeners(CleanupListener(h))
        h.install()
        rng = np.random.default_rng(0)
        ds = DataSet(rng.normal(0, 1, (16, 4)).astype(np.float32),
                     np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)])
        m.fit(ds, epochs=1, batch_size=8)
        assert signal.getsignal(signal.SIGUSR2) == prev
        h.uninstall()                              # second call: no-op
        h.uninstall()                              # third: still no-op
        assert signal.getsignal(signal.SIGUSR2) == prev
        # and the handler can be re-armed afterwards
        h.install()
        h.uninstall()
        assert signal.getsignal(signal.SIGUSR2) == prev


# -- data-plane fault site --------------------------------------------------

class TestDataFaultSite:
    def test_next_batch_fault_surfaces_from_fit(self):
        from deeplearning4j_tpu.data import DataSet
        from deeplearning4j_tpu.data.iterator import ExistingDataSetIterator

        m = _model()
        rng = np.random.default_rng(0)
        batches = [
            DataSet(rng.normal(0, 1, (8, 4)).astype(np.float32),
                    np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)])
            for _ in range(3)
        ]
        faults.arm("data.next_batch:raise:nth=2")
        with pytest.raises(faults.InjectedFault):
            m.fit(ExistingDataSetIterator(batches), epochs=1)
        assert m.iteration == 1                    # one step landed first
        faults.disarm()
        m.fit(ExistingDataSetIterator(batches), epochs=1)
        assert m.iteration == 4                    # clean epoch after disarm


# -- supervisor: retry-exhausted vs evicted ---------------------------------

class _FakeProc:
    def __init__(self, rc):
        self._rc = rc

    def wait(self, timeout=None):
        return self._rc

    def poll(self):
        return self._rc


class _FakeServer:
    def __init__(self):
        self._lock = threading.Condition()
        self.expected = 0
        self.members = {}
        self.pending = {}
        self.evictions = []
        self.generation = 1
        self.heartbeat_timeout = 30.0


class TestSupervisorDistinguishesControlPlaneLoss:
    def test_lost_workers_respawn_without_shrinking(self):
        from deeplearning4j_tpu.train.elastic import (
            EXIT_CONTROL_PLANE_LOST,
            ElasticSupervisor,
        )

        srv = _FakeServer()
        gen_worlds = []
        rcs_by_gen = [[EXIT_CONTROL_PLANE_LOST, EXIT_CONTROL_PLANE_LOST],
                      [0, 0]]

        def spawn(i, world, generation):
            if i == 0:
                gen_worlds.append(world)
            return _FakeProc(rcs_by_gen[generation - 1][i])

        sup = ElasticSupervisor(spawn, srv, initial_world=2, min_world=2,
                                max_generations=3)
        t0 = time.perf_counter()
        sup.run(timeout=60)
        # no eviction-settle wall-clocking for pure control-plane losses
        assert time.perf_counter() - t0 < 5.0
        assert gen_worlds == [2, 2]                # world NOT shrunk
        assert sup.control_plane_losses == 2
        assert sup.generations_run == 2


# -- the end-to-end acceptance run ------------------------------------------

def _spawn_elastic(worker_id, coord, out, metrics_out, ckpt_dir, total_steps,
                   victim, die_at, fault_plan):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update(
        DL4JTPU_TEST_MODE="elastic",
        DL4JTPU_TEST_WORKER_ID=worker_id,
        DL4JTPU_TEST_COORD=coord,
        DL4JTPU_TEST_OUT=out,
        DL4JTPU_TEST_METRICS_OUT=metrics_out,
        DL4JTPU_TEST_TOTAL_STEPS=str(total_steps),
        DL4JTPU_TEST_CKPT_DIR=ckpt_dir,
        DL4JTPU_TEST_VICTIM=victim,
        DL4JTPU_TEST_DIE_AT_STEP=str(die_at),
        # wide enough for the abort to propagate (victim fail() rpc +
        # survivor heartbeat interval) even on a loaded CI box — the
        # survivor must exit at a step boundary, not wedge in a dead
        # collective
        DL4JTPU_TEST_STEP_SLEEP="0.6",
        DL4J_TPU_FAULT_PLAN=fault_plan,
    )
    return subprocess.Popen(
        [sys.executable, WORKER], env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )


def _prom_value(text, family, label_substr=""):
    """Sum of all samples of `family` whose label set contains
    label_substr."""
    total, found = 0.0, False
    for line in text.splitlines():
        if line.startswith(family) and label_substr in line:
            m = re.match(r"\S+\s+(\S+)$", line)
            if m:
                total += float(m.group(1))
                found = True
    return total if found else None


class TestFaultInjectionEndToEnd:
    def test_elastic_run_survives_dropped_rpcs_and_truncated_ckpt(self, tmp_path):
        """ISSUE 3 acceptance: every 3rd coordinator.rpc dropped + one
        checkpoint.write truncated; a 2-worker elastic run (one worker
        killed mid-generation) still completes, restores from the last
        VALID checkpoint, and the survivor's /metrics shows non-zero
        dl4jtpu_rpc_retries_total and dl4jtpu_ckpt_verify_failures_total."""
        from deeplearning4j_tpu.train.elastic import ElasticSupervisor

        ckpt_dir = str(tmp_path / "ckpts")
        out = str(tmp_path / "done.jsonl")
        metrics_out = str(tmp_path / "metrics")
        total_steps = 8
        plan = "coordinator.rpc:raise:every=3;checkpoint.write:truncate:nth=2"
        srv = CoordinatorServer(expected_workers=2, heartbeat_timeout=60).start()

        spawned = []

        def spawn_worker(i, world, generation):
            p = _spawn_elastic(
                f"w{i}", srv.address, out, metrics_out, ckpt_dir,
                total_steps, victim="w1", die_at=5, fault_plan=plan,
            )
            spawned.append(p)
            return p

        sup = ElasticSupervisor(
            spawn_worker, srv, initial_world=2, min_world=1, max_generations=3
        )
        try:
            sup.run(timeout=420)
        except Exception:
            logs = []
            for i, p in enumerate(spawned):
                if p.poll() is None:
                    p.kill()
                _, err = p.communicate()
                logs.append(f"--- worker {i} rc={p.returncode}\n"
                            f"{err.decode()[-2000:]}")
            pytest.fail("faulted elastic run failed\n" + "\n".join(logs))
        finally:
            srv.stop()
            for p in spawned:
                if p.poll() is None:
                    p.kill()
                p.communicate()

        # the run completed in a shrunken second generation
        assert sup.generations_run == 2
        with open(out) as f:
            finishers = {r["worker"]: r for r in map(json.loads, f)}
        assert set(finishers) == {"w0"}
        assert finishers["w0"]["generation"] == 2
        assert finishers["w0"]["world"] == 1
        assert finishers["w0"]["final_iteration"] == total_steps
        assert np.isfinite(finishers["w0"]["score"])

        # the step-4 checkpoint was the truncated one: the generation-2
        # restore had to fall back past it to the step-2 checkpoint, and
        # training still reached total_steps — the last-good fallback
        # did its job (a corrupt report did NOT abort the generation)

        # survivor metrics: retries happened, verification caught the
        # truncation, faults actually fired
        dumps = [p for p in os.listdir(str(tmp_path))
                 if os.path.basename(p).startswith("metrics.")]
        assert dumps, "no worker metrics dump"
        text = "\n".join(
            (tmp_path / d).read_text() for d in dumps
        )
        assert _prom_value(text, "dl4jtpu_rpc_retries_total") > 0
        assert _prom_value(text, "dl4jtpu_ckpt_verify_failures_total") > 0
        assert _prom_value(text, "dl4jtpu_faults_injected_total",
                           'site="coordinator.rpc"') > 0
