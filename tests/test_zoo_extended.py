"""Extended zoo tests: AlexNet, Darknet19, SqueezeNet, Xception,
InceptionResNetV1, TinyYOLO, YOLO2 + the YOLOv2 loss/decode machinery.

Pattern follows the reference's zoo tests: instantiate each model at reduced
input size / class count, run a forward pass, check output shape; train the
detectors on a tiny synthetic task to validate the loss end to end.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.conf.objdetect import (
    Yolo2OutputLayer,
    build_targets,
    non_max_suppression,
)


class TestClassifierZoo:
    def test_alexnet_forward(self):
        from deeplearning4j_tpu.zoo import AlexNet

        m = AlexNet(num_classes=7, height=96, width=96).init_model()
        out = m.output(np.zeros((2, 96, 96, 3), np.float32))
        assert out.shape == (2, 7)
        np.testing.assert_allclose(np.asarray(out).sum(axis=1), 1.0, rtol=1e-4)

    def test_darknet19_forward(self):
        from deeplearning4j_tpu.zoo import Darknet19

        m = Darknet19(num_classes=5, height=64, width=64).init_model()
        out = m.output(np.zeros((1, 64, 64, 3), np.float32))
        assert out.shape == (1, 5)

    def test_squeezenet_forward(self):
        from deeplearning4j_tpu.zoo import SqueezeNet

        m = SqueezeNet(num_classes=6, height=96, width=96).init_model()
        out = m.output(np.zeros((2, 96, 96, 3), np.float32))
        assert out.shape == (2, 6)

    def test_xception_forward(self):
        from deeplearning4j_tpu.zoo import Xception

        # 2 middle blocks keep the CPU test fast; full depth is config
        m = Xception(num_classes=4, height=96, width=96, middle_blocks=2).init_model()
        out = m.output(np.zeros((1, 96, 96, 3), np.float32))
        assert out.shape == (1, 4)

    def test_inception_resnet_v1_forward(self):
        from deeplearning4j_tpu.zoo import InceptionResNetV1

        m = InceptionResNetV1(num_classes=4, height=96, width=96,
                              blocks_a=1, blocks_b=1, blocks_c=1).init_model()
        out = m.output(np.zeros((1, 96, 96, 3), np.float32))
        assert out.shape == (1, 4)


class TestYoloMachinery:
    ANCHORS = ((1.0, 1.0), (2.5, 2.5))

    def test_build_targets_assignment(self):
        # one box at grid cell (2, 1), closer to anchor 0
        t = build_targets([[(1, 1.5, 2.25, 0.9, 1.1)]], 4, 4, self.ANCHORS, 3)
        assert t.shape == (1, 4, 4, 2, 8)
        assert t[0, 2, 1, 0, 0] == 1.0            # obj at (row=2, col=1), anchor 0
        assert abs(t[0, 2, 1, 0, 1] - 0.5) < 1e-6  # x offset in cell
        assert abs(t[0, 2, 1, 0, 2] - 0.25) < 1e-6
        assert t[0, 2, 1, 0, 5 + 1] == 1.0        # class one-hot
        assert t.sum() == pytest.approx(
            1.0 + 0.5 + 0.25 + np.log(0.9) + np.log(1.1) + 1.0, abs=1e-5
        )

    def test_loss_zero_when_perfect(self):
        layer = Yolo2OutputLayer(anchors=self.ANCHORS, num_classes=2)
        targets = build_targets([[(0, 0.5, 0.5, 1.0, 1.0)]], 2, 2, self.ANCHORS, 2)
        # construct raw preds that invert to the targets exactly:
        # sigmoid(0)=0.5 offsets, tw=th=log(1/anchor)=0, big logits for conf/class
        raw = np.zeros((1, 2, 2, 2, 7), np.float32)
        raw[..., 4] = -20.0                       # no-object conf -> sigmoid ~ 0
        raw[0, 0, 0, 0, 4] = 20.0                 # responsible anchor conf -> ~1
        raw[0, 0, 0, 0, 5] = 20.0                 # class 0 logit
        loss = float(layer.compute_loss(raw.reshape(1, 2, 2, -1), targets))
        assert loss < 1e-4, loss

    def test_decode_geometry(self):
        layer = Yolo2OutputLayer(anchors=self.ANCHORS, num_classes=2)
        raw = np.zeros((1, 3, 3, 2 * 7), np.float32)
        d = layer.decode(raw)
        # sigmoid(0)=0.5 -> box centers at cell centers
        assert np.asarray(d["xy"])[0, 1, 2, 0].tolist() == [2.5, 1.5]
        np.testing.assert_allclose(np.asarray(d["wh"])[0, 0, 0, 0], [1.0, 1.0])
        np.testing.assert_allclose(np.asarray(d["wh"])[0, 0, 0, 1], [2.5, 2.5])

    def test_nms(self):
        boxes = np.array([[5, 5, 4, 4], [5.2, 5.2, 4, 4], [20, 20, 4, 4]], np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        keep = non_max_suppression(boxes, scores, iou_threshold=0.45, score_threshold=0.1)
        assert keep == [0, 2]

    def test_tiny_detector_learns(self):
        """A small sequential conv net + Yolo2OutputLayer on a synthetic
        one-box task: loss decreases, decode finds the box."""
        from deeplearning4j_tpu.models import SequentialModel
        from deeplearning4j_tpu.nn.activations import Activation
        from deeplearning4j_tpu.nn.conf import (
            BatchNorm, Conv2D, InputType, NeuralNetConfiguration, PoolingType, Subsampling,
        )
        from deeplearning4j_tpu.nn.updaters import Adam

        anchors = ((1.5, 1.5),)
        ncls = 2
        grid = 4
        conf = (
            NeuralNetConfiguration.builder()
            .seed(3)
            .updater(Adam(1e-2))
            .list()
            .layer(Conv2D(n_out=8, kernel=(3, 3), padding="same", activation=Activation.RELU))
            .layer(Subsampling(pooling=PoolingType.MAX, kernel=(2, 2), stride=(2, 2)))
            .layer(Conv2D(n_out=16, kernel=(3, 3), padding="same", activation=Activation.RELU))
            .layer(Subsampling(pooling=PoolingType.MAX, kernel=(2, 2), stride=(2, 2)))
            .layer(Conv2D(name="head", n_out=len(anchors) * (5 + ncls), kernel=(1, 1)))
            .layer(Yolo2OutputLayer(name="yolo", anchors=anchors, num_classes=ncls))
            .set_input_type(InputType.convolutional(16, 16, 1))
            .build()
        )
        model = SequentialModel(conf).init()

        # synthetic: a bright 6x6 square somewhere; class = 0 if top half
        rng = np.random.default_rng(0)
        n = 64
        xs = np.zeros((n, 16, 16, 1), np.float32)
        boxes = []
        for i in range(n):
            r, c = rng.integers(2, 10), rng.integers(2, 10)
            xs[i, r : r + 6, c : c + 6, 0] = 1.0
            cy, cx = (r + 3) / 4.0, (c + 3) / 4.0     # grid units (16px/4cells)
            boxes.append([(0 if r < 6 else 1, cx, cy, 1.5, 1.5)])
        ys = build_targets(boxes, grid, grid, anchors, ncls)

        ds = DataSet(xs, ys)
        first = model.score(ds)
        for _ in range(250):
            model.fit_batch(ds)
        last = model.score(ds)
        assert last < first * 0.5, (first, last)

        # decode: the responsible cell must be confident and localize the box
        yolo = conf.layers[-1]
        raw = np.asarray(model.output(xs[:1]))
        d = yolo.decode(raw.reshape(1, grid, grid, -1))
        true_cls, cx, cy, _, _ = boxes[0][0]
        row, col = int(cy), int(cx)
        conf_map = np.asarray(d["conf"])[0]
        assert conf_map[row, col, 0] > 0.35, conf_map[row, col]
        assert conf_map[row, col, 0] >= conf_map.max() * 0.8
        xy = np.asarray(d["xy"])[0, row, col, 0]
        assert abs(xy[0] - cx) < 0.75 and abs(xy[1] - cy) < 0.75, (xy, cx, cy)


class TestYoloZooConfigs:
    def test_tiny_yolo_builds_and_shapes(self):
        from deeplearning4j_tpu.zoo import TinyYOLO

        m = TinyYOLO(num_classes=3, height=128, width=128).init_model()
        out = m.output(np.zeros((1, 128, 128, 3), np.float32))
        # 128 / 2^5 = 4 grid; 5 anchors * (5+3) = 40 channels
        assert np.asarray(out).shape == (1, 4, 4, 40)

    def test_yolo2_builds_and_shapes(self):
        from deeplearning4j_tpu.zoo import YOLO2

        m = YOLO2(num_classes=3, height=128, width=128).init_model()
        out = m.output(np.zeros((1, 128, 128, 3), np.float32))
        assert np.asarray(out).shape == (1, 4, 4, 40)


class TestGetPredictedObjects:
    """YoloUtils.getPredictedObjects role: raw grid -> DetectedObject
    lists through decode + threshold + NMS."""

    def test_synthetic_grid_detections(self):
        from deeplearning4j_tpu.nn.conf.objdetect import (
            Yolo2OutputLayer, get_predicted_objects,
        )

        layer = Yolo2OutputLayer(anchors=((1.0, 1.0), (2.0, 2.0)), num_classes=3)
        H = W = 4
        A, C = 2, 3
        raw = np.full((1, H, W, A * (5 + C)), -6.0, np.float32)  # all quiet
        # light up cell (1,2) anchor 0: high conf, class 2
        base = 0 * (5 + C)
        raw[0, 1, 2, base + 4] = 6.0                # objectness
        raw[0, 1, 2, base + 5 + 2] = 8.0            # class 2 logit
        # and a second object at (3,0) anchor 1, class 0
        base = 1 * (5 + C)
        raw[0, 3, 0, base + 4] = 6.0
        raw[0, 3, 0, base + 5 + 0] = 8.0
        dets = get_predicted_objects(layer, raw, score_threshold=0.5)
        assert len(dets) == 1
        found = {(d.class_index, round(d.center_x - 0.5), round(d.center_y - 0.5))
                 for d in dets[0]}
        assert (2, 2, 1) in found
        assert (0, 0, 3) in found
        assert len(dets[0]) == 2
        for d in dets[0]:
            tlx, tly = d.top_left()
            brx, bry = d.bottom_right()
            assert brx > tlx and bry > tly

    def test_nms_suppresses_duplicates(self):
        from deeplearning4j_tpu.nn.conf.objdetect import (
            Yolo2OutputLayer, get_predicted_objects,
        )

        # two anchors of the SAME size on the same cell -> same box twice
        layer = Yolo2OutputLayer(anchors=((1.5, 1.5), (1.5, 1.5)), num_classes=2)
        C = 2
        raw = np.full((1, 3, 3, 2 * (5 + C)), -6.0, np.float32)
        for a in range(2):
            base = a * (5 + C)
            raw[0, 1, 1, base + 4] = 6.0
            raw[0, 1, 1, base + 5] = 8.0
        dets = get_predicted_objects(layer, raw, score_threshold=0.5)
        assert len(dets[0]) == 1            # duplicate suppressed

    def test_different_classes_not_cross_suppressed(self):
        from deeplearning4j_tpu.nn.conf.objdetect import (
            Yolo2OutputLayer, get_predicted_objects,
        )

        # same-size anchors at the same cell, each voting a DIFFERENT class
        layer = Yolo2OutputLayer(anchors=((1.5, 1.5), (1.5, 1.5)), num_classes=2)
        C = 2
        raw = np.full((1, 3, 3, 2 * (5 + C)), -6.0, np.float32)
        for a, cls in ((0, 0), (1, 1)):
            base = a * (5 + C)
            raw[0, 1, 1, base + 4] = 6.0
            raw[0, 1, 1, base + 5 + cls] = 8.0
        dets = get_predicted_objects(layer, raw, score_threshold=0.5)
        assert {d.class_index for d in dets[0]} == {0, 1}   # both survive
