"""Preemption-aware checkpointing (§5.3 failure detection on TPU)."""

import os
import signal

import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.models import SequentialModel
from deeplearning4j_tpu.nn import Adam
from deeplearning4j_tpu.nn.conf import (
    Dense, InputType, NeuralNetConfiguration, OutputLayer,
)
from deeplearning4j_tpu.train.preemption import (
    PreemptionError,
    PreemptionHandler,
)
from deeplearning4j_tpu.train.sharded_checkpoint import ShardedCheckpointer


def _model():
    conf = (
        NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2))
        .list()
        .layer(Dense(n_out=8))
        .layer(OutputLayer(n_out=2))
        .set_input_type(InputType.feed_forward(4))
        .build()
    )
    return SequentialModel(conf).init()


def _data():
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (64, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 64)]
    return DataSet(x, y)


def test_trigger_saves_and_raises(tmp_path):
    m = _model()
    ckpt = ShardedCheckpointer(str(tmp_path / "p1"), async_save=False)
    handler = PreemptionHandler(ckpt)
    m.set_listeners(handler.listener())
    handler.trigger()
    with pytest.raises(PreemptionError):
        m.fit(_data(), epochs=5, batch_size=32)
    assert m.iteration >= 1                      # at least one step ran
    steps = ckpt.all_steps()
    assert steps, "no preemption checkpoint written"
    m2 = ckpt.restore_model(steps[-1])
    assert m2.iteration == steps[-1]
    handler.uninstall()
    ckpt.close()


def test_real_signal_sets_flag_and_checkpoint_lands(tmp_path):
    m = _model()
    ckpt = ShardedCheckpointer(str(tmp_path / "p2"), async_save=False)
    handler = PreemptionHandler(ckpt, signals=(signal.SIGUSR1,))
    m.set_listeners(handler.listener())
    ds = _data()
    m.fit_batch(ds)                               # warm up / one clean step
    os.kill(os.getpid(), signal.SIGUSR1)
    assert handler.preempted
    with pytest.raises(PreemptionError):
        m.fit_batch(ds)
    assert ckpt.all_steps()
    handler.uninstall()
    ckpt.close()


def test_no_raise_mode_continues(tmp_path):
    saves = []
    m = _model()
    handler = PreemptionHandler(raise_after_save=False,
                                on_preempt=lambda model: saves.append(model.iteration))
    m.set_listeners(handler.listener())
    handler.trigger()
    m.fit(_data(), epochs=1, batch_size=32)       # runs to completion
    assert saves and saves[0] >= 0
    handler.uninstall()


def test_uninstall_restores_previous_handler():
    prev = signal.getsignal(signal.SIGUSR2)
    h = PreemptionHandler(signals=(signal.SIGUSR2,)).install()
    assert signal.getsignal(signal.SIGUSR2) == h._on_signal
    h.uninstall()
    assert signal.getsignal(signal.SIGUSR2) == prev
