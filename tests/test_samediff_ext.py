"""SameDiff extension tests: new namespaces, control flow, validation harness.

Mirrors the reference's SameDiff op tests + OpValidation pattern
(SURVEY.md §4.1): per-op forward expectations, finite-difference gradient
checks, control-flow semantics.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import (
    OpValidation,
    SameDiff,
    TestCase,
    gradient_check,
)


class TestNamespaces:
    def test_cnn_conv1d_shapes(self):
        sd = SameDiff()
        x = sd.placeholder("x")
        w = sd.var("w", np.random.default_rng(0).normal(size=(3, 4, 8)).astype(np.float32) * 0.1)
        out = sd.cnn.conv1d(x, w, name="y")
        y = sd.output({"x": np.zeros((2, 16, 4), np.float32)}, "y")
        assert np.asarray(y).shape == (2, 16, 8)
        del out

    def test_cnn_depthwise_and_deconv(self):
        rng = np.random.default_rng(1)
        sd = SameDiff()
        x = sd.placeholder("x")
        wd = sd.var("wd", rng.normal(size=(3, 3, 4, 2)).astype(np.float32) * 0.1)
        sd.cnn.depthwise_conv2d(x, wd, name="dw")
        y = sd.output({"x": np.ones((1, 8, 8, 4), np.float32)}, "dw")
        assert np.asarray(y).shape == (1, 8, 8, 8)  # C * multiplier

        sd2 = SameDiff()
        x2 = sd2.placeholder("x")
        wt = sd2.var("wt", rng.normal(size=(2, 2, 4, 6)).astype(np.float32) * 0.1)
        sd2.cnn.deconv2d(x2, wt, stride=(2, 2), name="up")
        y2 = sd2.output({"x": np.ones((1, 5, 5, 4), np.float32)}, "up")
        assert np.asarray(y2).shape == (1, 10, 10, 6)

    def test_rnn_lstm_cell_math(self):
        rng = np.random.default_rng(2)
        n, i, h = 2, 3, 4
        x = rng.normal(size=(n, i)).astype(np.float32)
        h0 = np.zeros((n, h), np.float32)
        c0 = np.zeros((n, h), np.float32)
        w = rng.normal(size=(i, 4 * h)).astype(np.float32)
        r = rng.normal(size=(h, 4 * h)).astype(np.float32)
        b = np.zeros(4 * h, np.float32)
        sd = SameDiff()
        px = sd.placeholder("x")
        sd.rnn.lstm_cell(px, sd.constant("h0", h0), sd.constant("c0", c0),
                         sd.constant("w", w), sd.constant("r", r), sd.constant("b", b),
                         name="hc")
        out = np.asarray(sd.output({"x": x}, "hc"))
        assert out.shape == (2, n, h)
        # hand-computed expectation
        z = x @ w + h0 @ r + b
        ii, ff, gg, oo = np.split(z, 4, axis=-1)
        sig = lambda t: 1 / (1 + np.exp(-t))
        c_new = sig(ff) * c0 + sig(ii) * np.tanh(gg)
        h_new = sig(oo) * np.tanh(c_new)
        np.testing.assert_allclose(out[0], h_new, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(out[1], c_new, rtol=1e-4, atol=1e-5)

    def test_image_ops(self):
        sd = SameDiff()
        x = sd.placeholder("x")
        sd.image.resize(x, size=(4, 4), name="r")
        sd.image.rgb_to_grayscale(x, name="g")
        sd.image.flip_lr(x, name="f")
        img = np.arange(2 * 2 * 2 * 3, dtype=np.float32).reshape(2, 2, 2, 3)
        r, g, f = sd.output({"x": img}, "r", "g", "f")
        assert np.asarray(r).shape == (2, 4, 4, 3)
        assert np.asarray(g).shape == (2, 2, 2, 1)
        np.testing.assert_array_equal(np.asarray(f), img[:, :, ::-1, :])

    def test_linalg_ops(self):
        rng = np.random.default_rng(3)
        a = rng.normal(size=(4, 4)).astype(np.float32)
        a = a @ a.T + 4 * np.eye(4, dtype=np.float32)  # SPD
        sd = SameDiff()
        pa = sd.constant("a", a)
        sd.linalg.inv(pa, name="inv")
        sd.linalg.cholesky(pa, name="chol")
        sd.linalg.det(pa, name="det")
        inv, chol, det = sd.output({}, "inv", "chol", "det")
        np.testing.assert_allclose(np.asarray(inv) @ a, np.eye(4), atol=1e-3)
        np.testing.assert_allclose(np.asarray(chol) @ np.asarray(chol).T, a, rtol=1e-3, atol=1e-3)
        assert float(det) == pytest.approx(float(np.linalg.det(a)), rel=1e-3)

    def test_bitwise_ops(self):
        sd = SameDiff()
        a = sd.constant("a", np.array([0b1100, 0b1010], np.int32))
        b = sd.constant("b", np.array([0b1010, 0b0110], np.int32))
        sd.bitwise.bitwise_and(a, b, name="and_")
        sd.bitwise.bitwise_xor(a, b, name="xor_")
        sd.bitwise.left_shift(a, bits=1, name="shl")
        and_, xor_, shl = sd.output({}, "and_", "xor_", "shl")
        np.testing.assert_array_equal(np.asarray(and_), [0b1000, 0b0010])
        np.testing.assert_array_equal(np.asarray(xor_), [0b0110, 0b1100])
        np.testing.assert_array_equal(np.asarray(shl), [0b11000, 0b10100])


class TestControlFlow:
    def test_if_cond(self):
        import jax.numpy as jnp

        sd = SameDiff()
        x = sd.placeholder("x")
        pred = sd.placeholder("p")
        sd.if_cond(pred, lambda v: v * 2.0, lambda v: v - 1.0, x, name="y")
        y_true = sd.output({"x": np.array([3.0], np.float32), "p": np.array(True)}, "y")
        y_false = sd.output({"x": np.array([3.0], np.float32), "p": np.array(False)}, "y")
        np.testing.assert_allclose(np.asarray(y_true), [6.0])
        np.testing.assert_allclose(np.asarray(y_false), [2.0])
        del jnp

    def test_while_loop(self):
        import jax.numpy as jnp

        sd = SameDiff()
        i0 = sd.constant("i0", np.array(0.0, np.float32))
        acc0 = sd.placeholder("acc0")
        i_f, acc_f = sd.while_loop(
            lambda i, acc: i < 5.0,
            lambda i, acc: (i + 1.0, acc + i),
            i0, acc0, name="loop",
        )
        out_i, out_acc = sd.output({"acc0": np.array(0.0, np.float32)}, i_f.name, acc_f.name)
        assert float(out_i) == 5.0
        assert float(out_acc) == 0 + 1 + 2 + 3 + 4
        del jnp

    def test_while_loop_bounded_scan_matches_and_differentiates(self):
        """max_trip lowers the loop to lax.scan: identical results to the
        unbounded while_loop, but reverse-mode differentiable."""
        import jax
        import jax.numpy as jnp

        def build(**kw):
            sd = SameDiff()
            x = sd.placeholder("x")
            i0 = sd.constant("i0", np.array(0, np.int32))
            _, acc = sd.while_loop(
                lambda i, a: i < 6,
                lambda i, a: (i + 1, a * 1.5),
                i0, x, name="loop", **kw,
            )
            return sd, acc

        xv = np.array([2.0, -1.0], np.float32)
        ref_sd, ref_acc = build()
        want = np.asarray(ref_sd.output({"x": xv}, ref_acc.name))
        for kw in ({"max_trip": 6, "exact_trip": True},
                   {"max_trip": 10}):        # masked: 4 dead iterations
            sd, acc = build(**kw)
            got = np.asarray(sd.output({"x": xv}, acc.name))
            np.testing.assert_allclose(got, want, rtol=1e-6)

            def f(xval, _sd=sd, _a=acc.name):
                (o,) = _sd._execute({**_sd._values, "x": xval}, (_a,))
                return jnp.sum(o)

            g = jax.grad(f)(jnp.asarray(xv))
            np.testing.assert_allclose(np.asarray(g), [1.5 ** 6] * 2,
                                       rtol=1e-5)

    def test_masked_scan_gradient_survives_nan_body_past_termination(self):
        """Double-where guard: a body that goes NaN outside the
        predicate's domain (sqrt of a negative once the loop should have
        stopped) must not poison the gradient of the bounded lowering."""
        import jax
        import jax.numpy as jnp

        sd = SameDiff()
        x0 = sd.placeholder("x0")
        (xf,) = sd.while_loop(
            lambda x: x > 0.6,
            lambda x: (jnp.sqrt(x - 0.5),),
            x0, name="loop", max_trip=8,
        )

        def f(xv):
            (o,) = sd._execute({**sd._values, "x0": xv}, (xf.name,))
            return o

        v = jnp.float32(1.6)
        out = f(v)          # 1.6 -> 1.0488 -> 0.7408 -> 0.4908 (stop)
        assert 0.4 < float(out) < 0.6
        g = jax.grad(f)(v)
        assert np.isfinite(float(g)), g

    def test_control_flow_not_serializable(self, tmp_path):
        sd = SameDiff()
        x = sd.placeholder("x")
        sd.if_cond(sd.constant("p", np.array(True)), lambda v: v, lambda v: -v, x, name="y")
        with pytest.raises(ValueError, match="control-flow"):
            sd.save(str(tmp_path / "g.zip"))


class TestValidationHarness:
    def test_gradient_check_passes_correct_grad(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        params = {"w": rng.normal(size=(5, 3)).astype(np.float32),
                  "b": np.zeros(3, np.float32)}
        x = rng.normal(size=(7, 5)).astype(np.float32)

        def loss(p):
            return jnp.mean(jnp.square(x @ p["w"] + p["b"]))

        res = gradient_check(loss, params)
        assert res.passed, res.failures

    def test_gradient_check_catches_wrong_grad(self):
        import jax

        # a function with a deliberately wrong custom gradient
        @jax.custom_vjp
        def bad_square(x):
            return x * x

        def fwd(x):
            return x * x, x

        def bwd(x, g):
            return (g * 3.0 * x,)  # wrong: should be 2x

        bad_square.defvjp(fwd, bwd)
        import jax.numpy as jnp

        params = {"w": np.array([1.0, 2.0, -1.5], np.float32)}

        def loss(p):
            return jnp.sum(bad_square(p["w"]))

        res = gradient_check(loss, params)
        assert not res.passed
        assert res.max_rel_error > 0.2

    def test_opvalidation_testcase(self):
        rng = np.random.default_rng(1)
        sd = SameDiff()
        x = sd.placeholder("x")
        w = sd.var("w", rng.normal(size=(4, 2)).astype(np.float32))
        y = sd.math.matmul(x, w, name="y")
        labels = sd.placeholder("labels")
        loss = sd.loss.mse_loss(y, labels, name="loss")
        sd.set_loss(loss)
        xv = rng.normal(size=(3, 4)).astype(np.float32)
        lv = rng.normal(size=(3, 2)).astype(np.float32)
        tc = TestCase(
            sd,
            placeholders={"x": xv, "labels": lv},
            expected={"y": xv @ np.asarray(sd.get_value("w"))},
        )
        errors = OpValidation.validate(tc)
        assert errors == []
        assert "matmul" in OpValidation.coverage_report() or "coverage" in OpValidation.coverage_report()

    def test_opvalidation_detects_forward_mismatch(self):
        sd = SameDiff()
        x = sd.placeholder("x")
        sd.math.square(x, name="y")
        tc = TestCase(
            sd,
            placeholders={"x": np.array([2.0], np.float32)},
            expected={"y": np.array([5.0], np.float32)},  # wrong: 4.0
            gradient_check=False,
        )
        errors = OpValidation.validate(tc)
        assert errors and "mismatch" in errors[0]


class TestRegistryBreadth:
    """New op families: trig/hyperbolic, rounding, segments, ordering."""

    def test_trig_and_checks(self):
        sd = SameDiff()
        x = sd.placeholder("x")
        v = np.array([0.1, 0.5, -0.3], np.float32)
        for op, ref in [
            ("tan", np.tan), ("asin", np.arcsin), ("atan", np.arctan),
            ("sinh", np.sinh), ("cosh", np.cosh), ("atanh", np.arctanh),
            ("log1p", np.log1p), ("expm1", np.expm1),
        ]:
            y = sd.math.__getattr__(op)(x, name=f"y_{op}")
            got = np.asarray(sd.output({"x": v}, y.name))
            np.testing.assert_allclose(got, ref(v), rtol=1e-5, atol=1e-6,
                                       err_msg=op)
        y = sd.math.is_nan(x, name="nanchk")
        got = np.asarray(sd.output({"x": np.array([1.0, np.nan], np.float32)},
                                   "nanchk"))
        np.testing.assert_allclose(got, [0.0, 1.0])

    def test_segment_and_ordering(self):
        sd = SameDiff()
        x = sd.placeholder("x")
        ids = sd.constant("ids", np.array([0, 0, 1, 2], np.int32))
        s = sd.math.segment_sum(x, ids, num_segments=3, name="seg")
        got = np.asarray(sd.output(
            {"x": np.array([1.0, 2.0, 3.0, 4.0], np.float32)}, "seg"))
        np.testing.assert_allclose(got, [3.0, 3.0, 4.0])

        sd2 = SameDiff()
        x2 = sd2.placeholder("x")
        top = sd2.math.top_k_values(x2, k=2, name="top")
        got = np.asarray(sd2.output(
            {"x": np.array([[3.0, 1.0, 9.0]], np.float32)}, "top"))
        np.testing.assert_allclose(got, [[9.0, 3.0]])

        srt = sd2.math.sort(x2, descending=True, name="srt")
        got = np.asarray(sd2.output(
            {"x": np.array([[3.0, 1.0, 9.0]], np.float32)}, "srt"))
        np.testing.assert_allclose(got, [[9.0, 3.0, 1.0]])

    def test_new_losses(self):
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.losses import Loss, compute

        preds = jnp.asarray([[2.0, 4.0]])
        labels = jnp.asarray([[1.0, 5.0]])
        mape = float(compute(Loss.MAPE, preds, labels))
        np.testing.assert_allclose(mape, (100.0 + 20.0) / 2, rtol=1e-5)
        msle = float(compute(Loss.MSLE, preds, labels))
        ref = np.mean((np.log1p([1.0, 5.0]) - np.log1p([2.0, 4.0])) ** 2)
        np.testing.assert_allclose(msle, ref, rtol=1e-5)
        w = float(compute(Loss.WASSERSTEIN, preds,
                          jnp.asarray([[1.0, -1.0]])))
        np.testing.assert_allclose(w, (-2.0 + 4.0) / 2, rtol=1e-5)


def test_round3_namespaces():
    """The round-3 op families are reachable through the typed namespaces
    (sd.signal is new; loss/linalg/image/random/math grew)."""
    import numpy as np

    from deeplearning4j_tpu.autodiff.samediff import SameDiff

    sd = SameDiff()
    x = sd.placeholder("x")
    sd.signal.stft(x, frame_length=16, frame_step=8, name="spec")
    sig = np.random.default_rng(0).normal(size=(2, 64)).astype(np.float32)
    spec = np.asarray(sd.output({"x": sig}, "spec"))
    assert spec.shape == (2, 7, 9)

    sd2 = SameDiff()
    p = sd2.placeholder("p")
    t = sd2.placeholder("t")
    sd2.loss.huber_loss(p, t, delta=1.0, name="l")
    out = float(np.asarray(sd2.output(
        {"p": np.ones((2, 3), np.float32), "t": np.zeros((2, 3), np.float32)},
        "l",
    )))
    assert abs(out - 0.5) < 1e-6

    sd3 = SameDiff()
    m = sd3.placeholder("m")
    sd3.linalg.logdet(m, name="ld")
    spd = 2.0 * np.eye(3, dtype=np.float32)
    assert abs(float(np.asarray(sd3.output({"m": spd}, "ld"))) - 3 * np.log(2)) < 1e-5
