"""SequentialModel end-to-end: the MultiLayerNetwork-role contract tests."""

import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet, NumpyDataSetIterator
from deeplearning4j_tpu.models import SequentialModel
from deeplearning4j_tpu.nn import Adam, Sgd
from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    BatchNorm,
    Conv2D,
    Dense,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
    Subsampling,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.train import CollectScoresListener


def two_moons(n=512, seed=0):
    """Simple separable 2-class problem."""
    rng = np.random.default_rng(seed)
    theta = rng.uniform(0, np.pi, n)
    cls = rng.integers(0, 2, n)
    x = np.stack(
        [
            np.cos(theta) + cls * 1.0 + rng.normal(0, 0.1, n),
            np.sin(theta) * (1 - 2 * cls) + rng.normal(0, 0.1, n),
        ],
        axis=1,
    ).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[cls]
    return x, y


def mlp_conf(updater=None, seed=12345):
    return (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater or Adam(1e-2))
        .weight_init(WeightInit.XAVIER)
        .activation(Activation.RELU)
        .list()
        .layer(Dense(n_out=32))
        .layer(Dense(n_out=32))
        .layer(OutputLayer(n_out=2, loss=Loss.MCXENT, activation=Activation.SOFTMAX))
        .set_input_type(InputType.feed_forward(2))
        .build()
    )


def test_mlp_learns_two_moons():
    x, y = two_moons()
    model = SequentialModel(mlp_conf()).init()
    scores = CollectScoresListener()
    model.set_listeners(scores)
    model.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=1), epochs=30)
    first = scores.scores[0][1]
    last = scores.scores[-1][1]
    assert last < first * 0.5, f"loss did not drop: {first} -> {last}"
    ev = model.evaluate(DataSet(x, y))
    assert ev.accuracy() > 0.95


def test_steps_per_execution_matches_per_batch_fit():
    """steps_per_execution=k compiles k optimizer steps into one program
    (scan over stacked batches); params, iteration count and listener
    stream must match the per-batch path exactly."""
    from deeplearning4j_tpu.train.listeners import CollectScoresListener

    x, y = two_moons(256)
    it = lambda: NumpyDataSetIterator(x, y, batch_size=32, seed=9)
    ref = SequentialModel(mlp_conf(seed=3)).init()
    ref_scores = CollectScoresListener()
    ref.set_listeners(ref_scores)
    ref.fit(it(), epochs=2)

    grp = SequentialModel(mlp_conf(seed=3)).init()
    grp_scores = CollectScoresListener()
    grp.set_listeners(grp_scores)
    grp.fit(it(), epochs=2, steps_per_execution=4)

    assert grp.iteration == ref.iteration
    assert ("train_multi",) in grp._step_fns
    assert [i for i, _ in grp_scores.scores] == [i for i, _ in ref_scores.scores]
    np.testing.assert_allclose(
        [s for _, s in grp_scores.scores], [s for _, s in ref_scores.scores],
        rtol=1e-4, atol=1e-6,
    )
    for k in ref.params:
        for p in ref.params[k]:
            np.testing.assert_allclose(
                np.asarray(grp.params[k][p]), np.asarray(ref.params[k][p]),
                rtol=2e-4, atol=1e-6,
                err_msg=f"{k}/{p} diverged under steps_per_execution",
            )


def test_steps_per_execution_ragged_tail():
    """249 examples / batch 32 = 7 full batches + a ragged one; the tail
    must train too (single-step fallback), with the right iteration count."""
    x, y = two_moons(249)
    m = SequentialModel(mlp_conf(seed=4)).init()
    m.fit(NumpyDataSetIterator(x, y, batch_size=32, seed=2), epochs=1,
          steps_per_execution=3)
    assert m.iteration == 8
    assert np.isfinite(float(m.score_value))


def test_output_probabilities_sum_to_one():
    x, y = two_moons(64)
    model = SequentialModel(mlp_conf()).init()
    out = np.asarray(model.output(x))
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-4)


def test_deterministic_init_and_fit():
    x, y = two_moons(128)
    it = lambda: NumpyDataSetIterator(x, y, batch_size=32, seed=5)
    m1 = SequentialModel(mlp_conf(seed=7)).init()
    m2 = SequentialModel(mlp_conf(seed=7)).init()
    for k in m1.params:
        for p in m1.params[k]:
            np.testing.assert_array_equal(
                np.asarray(m1.params[k][p]), np.asarray(m2.params[k][p])
            )
    m1.fit(it(), epochs=2)
    m2.fit(it(), epochs=2)
    np.testing.assert_allclose(
        np.asarray(m1.params["layer0"]["W"]),
        np.asarray(m2.params["layer0"]["W"]),
        rtol=1e-6,
    )


def test_small_cnn_runs_and_learns():
    rng = np.random.default_rng(0)
    # toy images: class 0 bright top half, class 1 bright bottom half
    n = 256
    cls = rng.integers(0, 2, n)
    x = rng.normal(0, 0.3, (n, 8, 8, 1)).astype(np.float32)
    for i, c in enumerate(cls):
        if c == 0:
            x[i, :4] += 1.0
        else:
            x[i, 4:] += 1.0
    y = np.eye(2, dtype=np.float32)[cls]
    conf = (
        NeuralNetConfiguration.builder()
        .seed(1)
        .updater(Adam(5e-3))
        .activation(Activation.RELU)
        .list()
        .layer(Conv2D(n_out=4, kernel=(3, 3)))
        .layer(Subsampling(kernel=(2, 2), stride=(2, 2)))
        .layer(BatchNorm())
        .layer(Dense(n_out=16))
        .layer(OutputLayer(n_out=2, loss=Loss.MCXENT, activation=Activation.SOFTMAX))
        .set_input_type(InputType.convolutional(8, 8, 1))
        .build()
    )
    model = SequentialModel(conf).init()
    model.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=2), epochs=15)
    assert model.evaluate(DataSet(x, y)).accuracy() > 0.9
    # BN running stats were updated inside the compiled step
    assert np.any(np.asarray(model.net_state["layer2"]["mean"]) != 0.0)


def test_num_params_and_param_table():
    model = SequentialModel(mlp_conf()).init()
    # 2*32+32 + 32*32+32 + 32*2+2 = 96+32+1024+32+64+2
    assert model.num_params() == (2 * 32 + 32) + (32 * 32 + 32) + (32 * 2 + 2)
    table = model.param_table()
    assert "layer0.W" in table and table["layer0.W"].shape == (2, 32)


def test_frozen_layer_not_updated():
    x, y = two_moons(128)
    conf = (
        NeuralNetConfiguration.builder()
        .seed(3)
        .updater(Sgd(0.1))
        .list()
        .layer(Dense(n_out=8, frozen=True, activation=Activation.RELU))
        .layer(OutputLayer(n_out=2, loss=Loss.MCXENT, activation=Activation.SOFTMAX))
        .set_input_type(InputType.feed_forward(2))
        .build()
    )
    model = SequentialModel(conf).init()
    w_before = np.asarray(model.params["layer0"]["W"]).copy()
    model.fit(NumpyDataSetIterator(x, y, batch_size=32), epochs=2)
    np.testing.assert_array_equal(np.asarray(model.params["layer0"]["W"]), w_before)
    assert not np.array_equal(
        np.asarray(model.params["layer1"]["W"]),
        w_before[: 8, :2] if False else np.asarray(model.params["layer1"]["W"]) * 0,
    )


def test_l2_regularization_shrinks_weights():
    x, y = two_moons(256)
    conf_plain = mlp_conf(seed=11)
    conf_reg = (
        NeuralNetConfiguration.builder()
        .seed(11)
        .updater(Adam(1e-2))
        .weight_init(WeightInit.XAVIER)
        .activation(Activation.RELU)
        .l2(0.5)
        .list()
        .layer(Dense(n_out=32))
        .layer(Dense(n_out=32))
        .layer(OutputLayer(n_out=2, loss=Loss.MCXENT, activation=Activation.SOFTMAX))
        .set_input_type(InputType.feed_forward(2))
        .build()
    )
    m_plain = SequentialModel(conf_plain).init()
    m_reg = SequentialModel(conf_reg).init()
    it = lambda: NumpyDataSetIterator(x, y, batch_size=64, seed=4)
    m_plain.fit(it(), epochs=10)
    m_reg.fit(it(), epochs=10)
    norm_plain = np.linalg.norm(np.asarray(m_plain.params["layer0"]["W"]))
    norm_reg = np.linalg.norm(np.asarray(m_reg.params["layer0"]["W"]))
    assert norm_reg < norm_plain


def test_score_and_masked_loss():
    x, y = two_moons(64)
    model = SequentialModel(mlp_conf()).init()
    s = model.score(DataSet(x, y))
    assert np.isfinite(s) and s > 0
    # mask out half the examples
    mask = np.zeros((64,), np.float32)
    mask[:32] = 1.0
    ds = DataSet(x, y, labels_mask=mask)
    model.fit_batch(ds)  # must not crash; masked mean over 32 examples
    assert np.isfinite(model.score_value)
