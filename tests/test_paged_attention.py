"""ISSUE 16 — paged-attention kernel parity.

The paged pools + page table are the serving plane's KV layout; this
file holds the three implementations to each other and to the dense
`_block_step` numerics: the XLA gather reference IS the contract, the
Pallas online-softmax kernel (interpret mode on CPU) must match it to
float tolerance, and the fused int8 path must match dequantize-then-
attend exactly (the dequant is algebraically hoisted, not
approximated).  Masking is load-bearing: garbage rows past ``seq_len``
and idle slots (seq_len 0 parked on the scratch page) must never leak
into an output.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.paged_attention import (
    IMPLS,
    paged_attention,
    select_impl,
)
from deeplearning4j_tpu.serving.kv_cache import quantize_page_rows

pytestmark = pytest.mark.generation

S, H, DH = 4, 2, 8          # slots, heads, head_dim
P, PS, MAXP = 24, 4, 5      # pool pages, page size, table width


def _case(seed=0, seq_lens=(7, 1, 13, 4)):
    """One random decode step: q rows, full pools, a page table whose
    entries are distinct pages, and per-slot live lengths."""
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((S, H, DH)).astype(np.float32)
    k_pages = rng.standard_normal((P, PS, H, DH)).astype(np.float32)
    v_pages = rng.standard_normal((P, PS, H, DH)).astype(np.float32)
    tbl = rng.permutation(np.arange(1, P))[: S * MAXP].reshape(S, MAXP)
    return (jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(tbl.astype(np.int32)),
            jnp.asarray(np.array(seq_lens, np.int32)))


def _dense_reference(q, k_pages, v_pages, tbl, seq_lens):
    """Per-slot dense softmax attention over the gathered live rows —
    `ops.generation._block_step`'s numerics, computed independently."""
    q, kp, vp = map(np.asarray, (q, k_pages, v_pages))
    tbl, seq_lens = np.asarray(tbl), np.asarray(seq_lens)
    out = np.zeros_like(q)
    for s in range(S):
        n = int(seq_lens[s])
        if n == 0:
            continue
        rows_k = np.concatenate([kp[p] for p in tbl[s]], axis=0)[:n]
        rows_v = np.concatenate([vp[p] for p in tbl[s]], axis=0)[:n]
        for h in range(H):
            scores = rows_k[:, h] @ q[s, h] / np.sqrt(DH)
            p = np.exp(scores - scores.max())
            p /= p.sum()
            out[s, h] = p @ rows_v[:, h]
    return out


class TestF32Parity:
    def test_xla_matches_dense_reference(self):
        q, kp, vp, tbl, lens = _case()
        got = np.asarray(
            paged_attention(q, kp, vp, tbl, lens, impl="xla"))
        np.testing.assert_allclose(
            got, _dense_reference(q, kp, vp, tbl, lens),
            rtol=1e-5, atol=1e-5)

    def test_pallas_matches_xla(self):
        q, kp, vp, tbl, lens = _case(seed=1)
        ref = np.asarray(paged_attention(q, kp, vp, tbl, lens, impl="xla"))
        got = np.asarray(paged_attention(
            q, kp, vp, tbl, lens, impl="pallas", interpret=True))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_garbage_rows_past_seq_len_are_masked(self):
        """Poisoning every row past each slot's live length (the exact
        rows a recycled page carries) must not move any output."""
        q, kp, vp, tbl, lens = _case(seed=2)
        kp2, vp2 = np.asarray(kp).copy(), np.asarray(vp).copy()
        for s in range(S):
            n = int(np.asarray(lens)[s])
            for j, p in enumerate(np.asarray(tbl)[s]):
                for r in range(PS):
                    if j * PS + r >= n:
                        kp2[p, r] = 1e4
                        vp2[p, r] = -1e4
        for impl, kw in (("xla", {}), ("pallas", {"interpret": True})):
            a = np.asarray(paged_attention(q, kp, vp, tbl, lens,
                                           impl=impl, **kw))
            b = np.asarray(paged_attention(
                q, jnp.asarray(kp2), jnp.asarray(vp2), tbl, lens,
                impl=impl, **kw))
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6,
                                       err_msg=impl)

    def test_idle_slot_is_finite(self):
        """seq_len 0 (an idle decode slot on the scratch page) must
        produce FINITE output — a plain softmax would nan a fully
        masked row, and one nan row would poison the whole fused step.
        The engine discards idle rows via its active mask, so the two
        impls may differ in the garbage VALUE (xla zeros it, pallas's
        online softmax leaves uniform-weight garbage); live slots must
        still agree exactly."""
        q, kp, vp, tbl, lens = _case(seed=3, seq_lens=(0, 5, 0, 2))
        outs = {}
        for impl, kw in (("xla", {}), ("pallas", {"interpret": True})):
            out = np.asarray(paged_attention(q, kp, vp, tbl, lens,
                                             impl=impl, **kw))
            assert np.isfinite(out).all(), impl
            outs[impl] = out
        np.testing.assert_allclose(outs["xla"][0], 0.0, atol=1e-6)
        np.testing.assert_allclose(outs["xla"][2], 0.0, atol=1e-6)
        for s in (1, 3):                      # the live slots
            np.testing.assert_allclose(
                outs["pallas"][s], outs["xla"][s], rtol=1e-5, atol=1e-6)


class TestInt8Parity:
    def _quantized(self, kp, vp):
        kq = np.zeros(np.asarray(kp).shape, np.int8)
        ks = np.ones(np.asarray(kp).shape[:-1], np.float32)
        vq, vs = kq.copy(), ks.copy()
        for p in range(P):
            kq[p], ks[p] = map(np.asarray, quantize_page_rows(kp[p]))
            vq[p], vs[p] = map(np.asarray, quantize_page_rows(vp[p]))
        return (jnp.asarray(kq), jnp.asarray(ks),
                jnp.asarray(vq), jnp.asarray(vs))

    def test_fused_matches_dequantize_then_attend(self):
        """The int8 kernels must equal attention over explicitly
        dequantized pools — fusion is a layout change, not a numerics
        change."""
        q, kp, vp, tbl, lens = _case(seed=4)
        kq, ks, vq, vs = self._quantized(kp, vp)
        deq_k = jnp.asarray(kq, jnp.float32) * ks[..., None]
        deq_v = jnp.asarray(vq, jnp.float32) * vs[..., None]
        ref = np.asarray(paged_attention(q, deq_k, deq_v, tbl, lens,
                                         impl="xla"))
        for impl, kw in (("xla", {}), ("pallas", {"interpret": True})):
            got = np.asarray(paged_attention(
                q, kq, vq, tbl, lens, k_scale=ks, v_scale=vs,
                impl=impl, **kw))
            np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5,
                                       err_msg=impl)

    def test_int8_tracks_f32_within_quant_error(self):
        q, kp, vp, tbl, lens = _case(seed=5)
        kq, ks, vq, vs = self._quantized(kp, vp)
        f32 = np.asarray(paged_attention(q, kp, vp, tbl, lens, impl="xla"))
        i8 = np.asarray(paged_attention(
            q, kq, vq, tbl, lens, k_scale=ks, v_scale=vs, impl="xla"))
        assert np.max(np.abs(f32 - i8)) < 0.15

    def test_scales_must_come_in_pairs(self):
        q, kp, vp, tbl, lens = _case()
        ks = jnp.ones((P, PS, H), jnp.float32)
        with pytest.raises(ValueError, match="BOTH"):
            paged_attention(q, kp, vp, tbl, lens, k_scale=ks)


class TestSelection:
    def test_env_override_wins(self, monkeypatch):
        from deeplearning4j_tpu.ops import paged_attention as pa

        monkeypatch.setenv(pa.ENV_KERNEL, "xla")
        assert select_impl() == "xla"
        monkeypatch.setenv(pa.ENV_KERNEL, "pallas")
        assert select_impl() == "pallas"

    def test_cpu_defaults_to_xla(self, monkeypatch):
        from deeplearning4j_tpu.ops import paged_attention as pa

        monkeypatch.delenv(pa.ENV_KERNEL, raising=False)
        assert select_impl() in IMPLS

    def test_selection_metric_counts(self):
        from deeplearning4j_tpu.observe.metrics import registry

        q, kp, vp, tbl, lens = _case()
        before = registry().counter(
            "dl4jtpu_paged_attention_total").value(impl="xla")
        paged_attention(q, kp, vp, tbl, lens, impl="xla")
        after = registry().counter(
            "dl4jtpu_paged_attention_total").value(impl="xla")
        assert after == before + 1
