"""ISSUE 20 — speculative decoding over the generation engine.

The contract under test is byte-level parity: the verify-once dispatch
samples the TARGET model at every chunk position with the baseline
``fold_in`` key schedule, so a speculative engine's output is
byte-identical to plain decode (and to `ops.generation.generate`) at
any temperature — drafts only change how many dispatches that output
costs.  Around that core: the drafter zoo (n-gram prompt lookup and the
two-model drafter), the ``serving.draft`` fault site (raise => latched
plain-decode fallback; corrupt => garbage drafts fully rejected),
speculative KV reservation/truncation with leak checks on every
rollback path, watchdog per-step normalization for multi-token
dispatches, and the zero-fresh-compile guarantee with both step
programs warm."""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.ops.generation import generate
from deeplearning4j_tpu.runtime import faults
from deeplearning4j_tpu.runtime.watchdog import StepWatchdog
from deeplearning4j_tpu.serving import speculative
from deeplearning4j_tpu.serving.generation import (
    GenerationConfig,
    GenerationEngine,
)
from deeplearning4j_tpu.serving.kv_cache import PagedKVCache, SCRATCH_PAGE
from deeplearning4j_tpu.zoo.transformer import TransformerEncoder

pytestmark = pytest.mark.generation

VOCAB, D, HEADS, LAYERS = 31, 16, 2, 2

CFG = dict(slots=4, page_size=8, num_pages=64, max_pages_per_seq=4,
           max_queue=16, default_max_new=8)


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.disarm()


@pytest.fixture(scope="module")
def model():
    return TransformerEncoder(
        vocab_size=VOCAB, d_model=D, n_heads=HEADS, n_layers=LAYERS,
        causal=True, seed=5,
    ).init_model()


@pytest.fixture(scope="module")
def draft_model():
    """A smaller, DIFFERENT transformer: drafts that are sometimes
    right, sometimes wrong — both accept and reject paths exercised."""
    return TransformerEncoder(
        vocab_size=VOCAB, d_model=8, n_heads=1, n_layers=1,
        causal=True, seed=9,
    ).init_model()


def _engine(model, **over):
    return GenerationEngine(
        model=model, config=GenerationConfig(**{**CFG, **over}))


def _dense(model, prompt, max_new, **kw):
    return np.asarray(
        generate(model, np.asarray(prompt)[None, :], max_new, **kw))[0]


def _prompt(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, VOCAB, n).astype(np.int32)


def _loopy_prompt(n, period=3, seed=0):
    """A prompt with short cycles — n-gram lookup drafts well on it."""
    base = np.random.default_rng(seed).integers(
        0, VOCAB, period).astype(np.int32)
    return np.tile(base, n // period + 1)[:n].copy()


# -- drafters ----------------------------------------------------------------

class TestDrafters:
    def test_ngram_longest_suffix_wins(self):
        d = speculative.NGramDrafter(max_n=3)
        h = np.asarray([1, 2, 3, 4, 1, 2, 3], np.int32)
        # trigram suffix [1,2,3] matched at the start; continuation 4...
        np.testing.assert_array_equal(d.draft(h, 3), [4, 1, 2])

    def test_ngram_degrades_to_shorter_grams(self):
        d = speculative.NGramDrafter(max_n=3)
        # no bigram/trigram repeat, but the unigram 5 recurs
        np.testing.assert_array_equal(
            d.draft(np.asarray([5, 6, 5], np.int32), 4), [6, 5])

    def test_ngram_empty_cases(self):
        d = speculative.NGramDrafter()
        assert d.draft(np.asarray([7], np.int32), 4).size == 0
        assert d.draft(np.asarray([1, 2, 3], np.int32), 0).size == 0
        # no suffix token ever recurs -> nothing to propose
        assert d.draft(np.asarray([1, 2, 3, 4], np.int32), 4).size == 0

    def test_model_drafter_is_deterministic(self, draft_model):
        d = speculative.ModelDrafter(draft_model)
        h = _prompt(6, seed=3)
        a, b = d.draft(h, 4), d.draft(h, 4)
        assert a.shape == (4,) and a.dtype == np.int32
        np.testing.assert_array_equal(a, b)

    def test_make_drafter_names_and_errors(self, draft_model):
        assert speculative.make_drafter("ngram").name == "ngram"
        assert speculative.make_drafter("prompt_lookup").name == "ngram"
        assert speculative.make_drafter(
            "model", draft_model=draft_model).name == "model"
        with pytest.raises(ValueError):
            speculative.make_drafter("model")        # needs a model
        with pytest.raises(ValueError):
            speculative.make_drafter("oracle")

    def test_spec_k_env_knob(self, monkeypatch):
        monkeypatch.delenv(speculative.ENV_SPEC_K, raising=False)
        assert speculative.spec_k_from_env(0) == 0
        monkeypatch.setenv(speculative.ENV_SPEC_K, "3")
        assert speculative.spec_k_from_env(0) == 3
        monkeypatch.setenv(speculative.ENV_SPEC_K, "-2")
        assert speculative.spec_k_from_env(0) == 0
        monkeypatch.setenv(speculative.ENV_SPEC_K, "four")
        assert speculative.spec_k_from_env(0) == 0


# -- speculative KV reservation ----------------------------------------------

class TestSpeculativeReservation:
    def _kv(self, **over):
        kw = dict(n_layers=2, n_heads=2, head_dim=8, num_pages=8,
                  page_size=8)
        kw.update(over)
        return PagedKVCache(**kw)

    def test_reserve_then_truncate_roundtrip(self):
        kv = self._kv()
        kv.alloc("a", 2)                       # 16 token positions
        got = kv.reserve_speculative("a", 16 + 8)   # 1 overhang page
        assert len(got) == 1 and len(kv.table("a")) == 3
        assert kv.stats()["spec_reserved_pages"] == 1
        freed = kv.truncate_to("a", 16)
        assert freed == got and len(kv.table("a")) == 2
        assert kv.stats()["spec_reserved_pages"] == 0
        kv.release("a")
        assert kv.leak_check() is None

    def test_reserve_is_best_effort_on_shortfall(self):
        kv = self._kv()
        kv.alloc("a", 6)                       # 6 of 7 usable pages
        kv.alloc("b", 1)
        assert kv.free_pages == 0
        assert kv.reserve_speculative("a", 8 * 7) == []
        assert kv.stats()["spec_reserved_pages"] == 0
        kv.release("a")
        kv.release("b")
        assert kv.leak_check() is None

    def test_release_drops_speculative_bookkeeping(self):
        kv = self._kv()
        kv.alloc("a", 1)
        kv.reserve_speculative("a", 8 + 8)
        kv.release("a")
        assert kv.used_pages == 0
        assert kv.stats()["spec_reserved_pages"] == 0
        assert kv.leak_check() is None


# -- byte parity with plain decode -------------------------------------------

class TestParity:
    def test_greedy_byte_identical_across_buckets(self, model):
        """Prompt lengths straddling the 8/16 prefill buckets, long
        generations, ngram drafting — every stream byte-equal to the
        dense reference, with real drafting having happened."""
        eng = _engine(model, spec_k=4).start()
        try:
            cases = [(_loopy_prompt(4, seed=1), 16),
                     (_loopy_prompt(8, seed=2), 20),
                     (_loopy_prompt(12, seed=3), 16),
                     (_prompt(7, seed=4), 12)]
            reqs = [eng.submit(p, m) for p, m in cases]
            for (p, m), r in zip(cases, reqs):
                np.testing.assert_array_equal(
                    np.asarray(r.result(120.0)), _dense(model, p, m))
            st = eng.stats()["speculative"]
            assert st["enabled"] and st["k"] == 4
            assert st["drafter"] == "ngram"
            assert st["drafted"] > 0 and st["accepted"] > 0
            assert st["verify_dispatches"] > 0
            assert eng.kv.leak_check() is None
        finally:
            eng.stop()

    def test_sampled_byte_identical(self, model):
        """Temperature + top-k + per-stream seeds: the verify chunk
        samples with the baseline fold_in schedule, so even REJECTED
        positions resample to the exact baseline token."""
        eng = _engine(model, spec_k=3).start()
        try:
            for seed in (0, 7, 42):
                p = _loopy_prompt(6, seed=seed)
                out = np.asarray(eng.submit(
                    p, 14, temperature=0.9, top_k=5, seed=seed,
                ).result(120.0))
                np.testing.assert_array_equal(
                    out, _dense(model, p, 14, temperature=0.9,
                                top_k=5, seed=seed))
            assert eng.stats()["speculative"]["drafted"] > 0
            assert eng.kv.leak_check() is None
        finally:
            eng.stop()

    def test_model_drafter_byte_identical(self, model, draft_model):
        eng = _engine(model, spec_k=2, spec_drafter="model",
                      spec_draft_model=draft_model).start()
        try:
            p = _prompt(5, seed=11)
            np.testing.assert_array_equal(
                np.asarray(eng.generate(p, 12, timeout=120.0)),
                _dense(model, p, 12))
            st = eng.stats()["speculative"]
            assert st["drafter"] == "model" and st["drafted"] > 0
            assert eng.kv.leak_check() is None
        finally:
            eng.stop()

    def test_int8_kv_speculative_decode_runs_leak_free(self, model):
        """int8 pages ride the same verify path (chunk attention with
        scale blocks); gated on agreement like the plain int8 engine,
        byte parity is an f32-only contract."""
        eng = _engine(model, spec_k=3, kv_dtype="int8").start()
        try:
            p = _loopy_prompt(5, seed=36)
            out = np.asarray(eng.generate(p, 12, timeout=120.0))
            ref = _dense(model, p, 12)
            m = min(len(out), len(ref))
            assert (out[:m] == ref[:m]).mean() >= 0.8
            assert eng.stats()["speculative"]["drafted"] > 0
            assert eng.kv.leak_check() is None
        finally:
            eng.stop()

    def test_per_request_spec_k_zero_is_plain(self, model):
        eng = _engine(model, spec_k=4).start()
        try:
            p = _loopy_prompt(6, seed=21)
            req = eng.submit(p, 10, spec_k=0)
            np.testing.assert_array_equal(
                np.asarray(req.result(120.0)), _dense(model, p, 10))
            assert req.spec_drafted == 0
        finally:
            eng.stop()

    def test_stop_tokens_respected_mid_chunk(self, model):
        """A stop token accepted inside a verify chunk must truncate
        the emitted run exactly where plain decode would stop."""
        p = _loopy_prompt(6, seed=31)
        ref = _dense(model, p, 12)
        gen = ref[len(p):]
        stop = int(gen[3])                     # stops 4 tokens in
        first = int(np.argmax(gen == stop))
        eng = _engine(model, spec_k=4).start()
        try:
            out = np.asarray(eng.submit(
                p, 12, stop_tokens=(stop,)).result(120.0))
            np.testing.assert_array_equal(
                out, ref[: len(p) + first + 1])
            assert out[-1] == stop
            assert eng.kv.leak_check() is None
        finally:
            eng.stop()


# -- distribution preservation at scale --------------------------------------

class TestDistributionPreservation:
    @pytest.mark.slow
    def test_seeded_sampling_histogram_parity_10k(self, model):
        """Per-position token histograms over >= 10k sampled tokens
        (420 seeded streams x 24 positions) are identical between the
        speculative engine and the dense reference — the rejection
        sampler provably preserves the output distribution."""
        n_streams, max_new = 420, 24
        p = _loopy_prompt(5, seed=100)
        eng = _engine(model, spec_k=3, max_queue=512).start()
        try:
            reqs = [eng.submit(p, max_new, temperature=1.0, seed=s)
                    for s in range(n_streams)]
            got = np.stack([
                np.asarray(r.result(600.0))[len(p):] for r in reqs])
            assert eng.kv.leak_check() is None
        finally:
            eng.stop()
        ref = np.stack([
            _dense(model, p, max_new, temperature=1.0, seed=s)[len(p):]
            for s in range(n_streams)])
        assert got.size >= 10_000
        for j in range(max_new):
            np.testing.assert_array_equal(
                np.bincount(got[:, j], minlength=VOCAB),
                np.bincount(ref[:, j], minlength=VOCAB),
                err_msg=f"histogram diverged at position {j}")


# -- the serving.draft fault site --------------------------------------------

class TestDraftFaults:
    @pytest.mark.faults
    def test_corrupt_drafts_all_rejected_output_unchanged(self, model):
        """Garbage drafts cost acceptance, never correctness: armed
        corrupt on EVERY draft, the output stays byte-identical and
        no page leaks."""
        eng = _engine(model, spec_k=4).start()
        try:
            faults.arm("serving.draft:corrupt:every=1")
            p = _loopy_prompt(6, seed=41)
            out = np.asarray(eng.generate(p, 12, timeout=120.0))
            faults.disarm()
            np.testing.assert_array_equal(out, _dense(model, p, 12))
            st = eng.stats()["speculative"]
            assert st["drafted"] > 0
            assert st["acceptance_ratio"] < 0.5   # garbage can't win
            assert eng.kv.leak_check() is None
        finally:
            eng.stop()

    @pytest.mark.faults
    def test_raise_latches_plain_fallback_mid_stream(self, model):
        """A drafter failure mid-stream disables speculation for THAT
        stream only: the overhang pages are truncated back, decode
        continues plain, and the output is still byte-identical."""
        eng = _engine(model, spec_k=4).start()
        try:
            faults.arm("serving.draft:raise:nth=2")
            p = _loopy_prompt(6, seed=51)
            req = eng.submit(p, 14)
            out = np.asarray(req.result(120.0))
            np.testing.assert_array_equal(out, _dense(model, p, 14))
            assert req.spec_disabled
            assert eng.stats()["speculative"]["fallbacks"] == 1
            assert eng.kv.stats()["spec_reserved_pages"] == 0
            assert eng.kv.leak_check() is None
            faults.disarm()
            # the NEXT stream drafts normally again
            req2 = eng.submit(_loopy_prompt(6, seed=52), 10)
            req2.result(120.0)
            assert not req2.spec_disabled
        finally:
            eng.stop()

    def test_cancel_mid_stream_releases_speculative_pages(self, model):
        eng = _engine(model, spec_k=4).start()
        try:
            req = eng.submit(_loopy_prompt(4, seed=61), 27)
            deadline = time.monotonic() + 60.0
            while not req.tokens_so_far():
                assert time.monotonic() < deadline
                time.sleep(0.01)
            req.cancel()
            while eng.kv.used_pages and time.monotonic() < deadline:
                time.sleep(0.01)
            assert eng.kv.used_pages == 0
            assert eng.kv.stats()["spec_reserved_pages"] == 0
            assert eng.kv.leak_check() is None
        finally:
            eng.stop()


# -- watchdog normalization for multi-token dispatches -----------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestWatchdogNormalization:
    def test_verify_dispatch_feeds_per_step_ewma(self):
        """A C-token verify dispatch disarmed with its full wall time
        must leave the same per-step EWMA a plain step would — a
        high-acceptance burst cannot stretch later plain deadlines."""
        clk = _Clock()
        wd = StepWatchdog(floor_s=0.001, cold_floor_s=10.0, k=10.0,
                          ewma_alpha=1.0, threaded=False, clock=clk)
        wd.arm(1, n_steps=5)
        clk.t += 0.5
        wd.disarm(0.5)
        assert wd.ewma == pytest.approx(0.1)
        assert wd.deadline_s() == pytest.approx(1.0)   # k * per, C=1

    def test_verify_deadline_scales_with_chunk_width(self):
        """The C-token dispatch gets a C-times deadline — a healthy
        verify step is never flagged just for being wider — while the
        following plain step's deadline snaps back to k*EWMA."""
        clk = _Clock()
        wd = StepWatchdog(floor_s=0.001, cold_floor_s=10.0, k=10.0,
                          ewma_alpha=1.0, threaded=False, clock=clk)
        wd.arm(1)
        clk.t += 0.1
        wd.disarm(0.1)                         # EWMA = 0.1s/step
        wd.arm(2, n_steps=5)                   # deadline 10*0.1*5 = 5s
        clk.t += 4.9
        wd.poll(now=clk.t)
        assert wd.events == []                 # within the wide deadline
        wd.disarm(0.5)
        wd.arm(3, n_steps=1)                   # back to 1s
        clk.t += 1.01
        wd.poll(now=clk.t)
        assert wd.events and wd.events[-1]["stage"] == "warn"
        assert wd.events[-1]["n_steps"] == 1
        wd.disarm(None)

    def test_tokens_generated_counts_emitted_not_dispatches(self, model):
        """The throughput SLI is per emitted token: a speculative run
        that emits N tokens reports N, however few dispatches it took."""
        eng = _engine(model, spec_k=4).start()
        try:
            out = np.asarray(
                eng.generate(_loopy_prompt(6, seed=71), 14,
                             timeout=120.0))
            st = eng.stats()
            emitted = out.shape[0] - 6
            assert st["tokens_generated"] == emitted
            spec = st["speculative"]
            dispatches = (spec["verify_dispatches"]
                          + spec["plain_dispatches"])
            assert dispatches < emitted        # speculation paid off
            assert spec["tokens_per_dispatch"] > 1.0
            # per-token latency attribution exists for every segment
            for seg in st["latency_breakdown"].values():
                assert "seconds_per_token" in seg
        finally:
            eng.stop()


# -- bounded program set -----------------------------------------------------

class TestSpecCompileStability:
    def test_zero_fresh_compiles_with_both_programs_warm(self, model):
        from deeplearning4j_tpu.runtime import compile_stats

        eng = _engine(model, spec_k=3).start()
        try:
            # warm: verify program (drafting stream), plain program
            # (spec_k=0 stream), and the 8/16 prefill buckets
            eng.generate(_loopy_prompt(6, seed=81), 8, timeout=120.0)
            eng.submit(_prompt(12, seed=82), 6, spec_k=0).result(120.0)
            snap = compile_stats.snapshot()
            reqs = [eng.submit(_loopy_prompt(3 + i, seed=83 + i), 5 + i,
                               temperature=float(i % 2) * 0.8,
                               top_k=(i % 3), seed=i,
                               spec_k=(None if i % 2 else 0))
                    for i in range(6)]
            for r in reqs:
                r.result(120.0)
            delta = compile_stats.snapshot() - snap
            assert delta.fresh_backend_compiles == 0, delta.as_dict()
        finally:
            eng.stop()


# -- HTTP knob ---------------------------------------------------------------

class TestHTTPSpecKnob:
    def test_spec_k_override_rides_the_generate_api(self, model):
        from deeplearning4j_tpu.serving.http import ServingHTTPServer
        from deeplearning4j_tpu.serving.server import InferenceServer

        srv = InferenceServer(model)
        eng = GenerationEngine(
            server=srv,
            config=GenerationConfig(**{**CFG, "spec_k": 4})).start()
        http = ServingHTTPServer(srv).start()
        try:
            p = _loopy_prompt(5, seed=92)
            body = json.dumps({"prompt": p.tolist(),
                               "max_new_tokens": 10,
                               "spec_k": 2}).encode()
            req = urllib.request.Request(
                http.url + "v1/generate", body,
                {"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as r:
                assert r.status == 200
                doc = json.loads(r.read())
            np.testing.assert_array_equal(
                np.asarray(doc["tokens"]), _dense(model, p, 10))
            bad = urllib.request.Request(
                http.url + "v1/generate",
                json.dumps({"prompt": p.tolist(),
                            "spec_k": "many"}).encode(),
                {"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(bad, timeout=120)
            assert ei.value.code == 400
            ei.value.close()                   # drop the error socket
        finally:
            http.stop()
            eng.stop()
            srv.stop()
