"""ISSUE 16 — token-level continuous-batching generation serving.

`ops.generation.generate` is the single-request reference; this file
holds `serving.generation.GenerationEngine` to it token-for-token
(greedy AND sampled — the engine reproduces the dense path's `fold_in`
RNG schedule exactly) while exercising the serving ladder around the
decode loop: paged KV allocation with an explicit ``kv_exhausted`` 429,
page-leak-free cancel/abort paths, watchdog wedge recovery, hot-swap
between decode steps with zero dropped streams, the three new fault
sites, the `/v1/generate` HTTP surface, and the prefill/decode
disaggregation seam (engine-to-engine and routed through a
`ServingFleet` with replica roles)."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.generation import generate
from deeplearning4j_tpu.runtime import faults
from deeplearning4j_tpu.serving.admission import (
    REJECT_STATUS,
    ServingError,
    ServingRejected,
)
from deeplearning4j_tpu.serving.generation import (
    GenerationConfig,
    GenerationEngine,
)
from deeplearning4j_tpu.serving.kv_cache import (
    SCRATCH_PAGE,
    KVPoolExhausted,
    PagedKVCache,
    quantize_page_rows,
)
from deeplearning4j_tpu.serving.server import InferenceServer
from deeplearning4j_tpu.zoo.transformer import TransformerEncoder

pytestmark = pytest.mark.generation

VOCAB, D, HEADS, LAYERS = 31, 16, 2, 2

#: the shared engine shape for most tests: 4 slots, 8-row pages, a
#: 4-wide page table -> streams up to 32 KV positions
CFG = dict(slots=4, page_size=8, num_pages=64, max_pages_per_seq=4,
           max_queue=16, default_max_new=8)


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.disarm()


@pytest.fixture(scope="module")
def model():
    return TransformerEncoder(
        vocab_size=VOCAB, d_model=D, n_heads=HEADS, n_layers=LAYERS,
        causal=True, seed=5,
    ).init_model()


def _engine(model, **over):
    return GenerationEngine(
        model=model, config=GenerationConfig(**{**CFG, **over}))


def _dense(model, prompt, max_new, **kw):
    """The reference row: ops.generation.generate on one prompt."""
    return np.asarray(
        generate(model, np.asarray(prompt)[None, :], max_new, **kw))[0]


def _prompt(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, VOCAB, n).astype(np.int32)


# -- the paged KV allocator --------------------------------------------------

class TestPagedKVCache:
    def _kv(self, **over):
        kw = dict(n_layers=2, n_heads=2, head_dim=8, num_pages=8,
                  page_size=8)
        kw.update(over)
        return PagedKVCache(**kw)

    def test_alloc_release_accounting(self):
        kv = self._kv()
        assert kv.free_pages == 7          # page 0 is scratch
        kv.alloc("a", 3)
        kv.alloc("b", 2)
        assert kv.used_pages == 5 and kv.free_pages == 2
        assert len(kv.table("a")) == 3
        assert SCRATCH_PAGE not in kv.table("a")
        kv.release("a")
        kv.release("a")                    # idempotent
        assert kv.used_pages == 2
        kv.release("b")
        assert kv.used_pages == 0 and kv.leak_check() is None

    def test_exhaustion_raises_and_rolls_back(self):
        kv = self._kv()
        kv.alloc("a", 6)
        with pytest.raises(KVPoolExhausted):
            kv.alloc("b", 2)
        # the failed alloc must not leak partial grants
        assert kv.used_pages == 6 and kv.leak_check() is None

    def test_pages_for_and_occupancy(self):
        kv = self._kv()
        assert kv.page_size == 8           # quantized to PAGE_QUANTUM
        assert kv.pages_for(1) == 1
        assert kv.pages_for(8) == 1
        assert kv.pages_for(9) == 2
        kv.alloc("a", 7)
        assert kv.occupancy() == pytest.approx(1.0)
        kv.release("a")
        assert kv.occupancy() == 0.0

    def test_write_prefill_round_trips(self):
        kv = self._kv()
        rng = np.random.default_rng(0)
        k = rng.standard_normal((2, 16, 2, 8)).astype(np.float32)
        v = rng.standard_normal((2, 16, 2, 8)).astype(np.float32)
        kv.alloc("a", 2)
        tbl = kv.write_prefill("a", k, v)
        got = np.concatenate(
            [np.asarray(kv.k_pages[:, p]) for p in tbl], axis=1)
        np.testing.assert_allclose(got, k, rtol=1e-6)

    def test_int8_pages_quantize_within_bound(self):
        kv = self._kv(kv_dtype="int8")
        rng = np.random.default_rng(1)
        k = rng.standard_normal((2, 16, 2, 8)).astype(np.float32)
        v = rng.standard_normal((2, 16, 2, 8)).astype(np.float32)
        kv.alloc("a", 2)
        tbl = kv.write_prefill("a", k, v)
        deq = np.concatenate(
            [np.asarray(kv.k_pages[:, p], np.float32)
             * np.asarray(kv.k_scales[:, p])[..., None]
             for p in tbl], axis=1)
        # symmetric int8: error bounded by half a quantization step
        assert np.max(np.abs(deq - k)) <= np.max(np.abs(k)) / 127.0

    def test_quantize_page_rows_zero_row_safe(self):
        q, s = quantize_page_rows(jnp.zeros((4, 2, 8)))
        assert np.all(np.asarray(q) == 0)
        assert np.all(np.asarray(s) == 1.0)   # never a 0-divide scale

    @pytest.mark.faults
    def test_kv_alloc_fault_site(self):
        kv = self._kv()
        faults.arm("kv.alloc:raise:nth=1")
        with pytest.raises(KVPoolExhausted):
            kv.alloc("a", 1)
        faults.disarm()
        kv.alloc("a", 1)                   # the pool itself is fine
        assert kv.used_pages == 1


# -- numerics: the engine vs the dense reference -----------------------------

class TestDecodeParity:
    def test_greedy_token_identical_to_dense(self, model):
        eng = _engine(model).start()
        try:
            for n, max_new in ((3, 6), (7, 12), (14, 10)):
                p = _prompt(n, seed=n)
                out = np.asarray(eng.generate(p, max_new, timeout=120.0))
                np.testing.assert_array_equal(
                    out, _dense(model, p, max_new), err_msg=f"len {n}")
        finally:
            eng.stop()

    @pytest.mark.slow
    def test_sampled_and_top_k_identical_to_dense(self, model):
        """Not statistically close — IDENTICAL: the engine reproduces
        the dense path's per-token `fold_in` schedule and top-k
        threshold rule exactly."""
        eng = _engine(model).start()
        try:
            p = _prompt(6, seed=9)
            for kw in (dict(temperature=1.0, seed=3),
                       dict(temperature=1.3, top_k=5, seed=7)):
                out = np.asarray(eng.generate(p, 10, timeout=120.0, **kw))
                np.testing.assert_array_equal(
                    out, _dense(model, p, 10, **kw), err_msg=str(kw))
        finally:
            eng.stop()

    @pytest.mark.slow
    def test_concurrent_streams_each_match_their_reference(self, model):
        """The continuous batch is airtight: slots never bleed into
        each other even with mixed lengths, budgets, and sampling."""
        eng = _engine(model, slots=3).start()
        try:
            specs = [
                (_prompt(3, seed=1), 8, dict()),
                (_prompt(9, seed=2), 14, dict(temperature=1.0, seed=4)),
                (_prompt(5, seed=3), 5, dict(temperature=0.9, top_k=4,
                                             seed=8)),
                (_prompt(12, seed=4), 11, dict()),
                (_prompt(4, seed=5), 9, dict(temperature=1.1, seed=2)),
            ]
            reqs = [eng.submit(p, n, **kw) for p, n, kw in specs]
            for req, (p, n, kw) in zip(reqs, specs):
                np.testing.assert_array_equal(
                    np.asarray(req.result(120.0)), _dense(model, p, n, **kw))
        finally:
            eng.stop()

    def test_stop_token_truncates_like_the_reference(self, model):
        p = _prompt(5, seed=6)
        ref = _dense(model, p, 12)
        gen = ref[len(p):]
        stop = int(gen[3])                 # stop on the 4th ref token
        eng = _engine(model).start()
        try:
            out = np.asarray(eng.generate(p, 12, stop_tokens=(stop,),
                                          timeout=120.0))
        finally:
            eng.stop()
        first = int(np.argmax(gen == stop))
        np.testing.assert_array_equal(out, ref[: len(p) + first + 1])
        assert out[-1] == stop

    @pytest.mark.slow
    def test_int8_kv_agreement_gate(self, model):
        """int8 KV pages are gated the way PR 13 gated PTQ: high greedy
        token agreement with the f32 reference, not bit equality."""
        eng = _engine(model, kv_dtype="int8").start()
        try:
            agree = total = 0
            for n in (4, 9):
                p = _prompt(n, seed=20 + n)
                ref = _dense(model, p, 12)[n:]
                out = np.asarray(eng.generate(p, 12, timeout=120.0))[n:]
                m = min(len(ref), len(out))
                agree += int((ref[:m] == out[:m]).sum())
                total += m
        finally:
            eng.stop()
        assert agree / total >= 0.9, f"int8 agreement {agree}/{total}"

    def test_ttft_is_recorded(self, model):
        eng = _engine(model).start()
        try:
            req = eng.submit(_prompt(4), 3)
            req.result(120.0)
            assert req.ttft_s is not None and req.ttft_s > 0
        finally:
            eng.stop()


# -- admission, capacity, and the explicit 429 -------------------------------

class TestAdmission:
    def test_over_capacity_stream_is_a_client_error(self, model):
        eng = _engine(model)
        with pytest.raises(ValueError, match="KV positions"):
            eng.submit(_prompt(8), 40)     # 48 > 4 pages x 8 rows
        with pytest.raises(ValueError, match="empty"):
            eng.submit(np.zeros(0, np.int32), 4)
        with pytest.raises(ValueError, match="max_new"):
            eng.submit(_prompt(4), 0)

    def test_kv_exhaustion_is_an_explicit_429(self, model):
        # 2 usable pages; the stream needs 3 -> admission answers
        # kv_exhausted instead of stalling on HBM that will not come
        eng = _engine(model, num_pages=3).start()
        try:
            req = eng.submit(_prompt(17), 4)
            with pytest.raises(ServingRejected) as ei:
                req.result(60.0)
        finally:
            eng.stop()
        assert ei.value.reason == "kv_exhausted"
        assert ei.value.status == 429
        assert REJECT_STATUS["kv_exhausted"] == 429

    def test_full_queue_rejects(self, model):
        eng = _engine(model, max_queue=2)   # not started: nothing drains
        eng.submit(_prompt(3), 2)
        eng.submit(_prompt(3), 2)
        with pytest.raises(ServingRejected) as ei:
            eng.submit(_prompt(3), 2)
        assert ei.value.reason == "queue_full"

    def test_cancel_releases_every_page(self, model):
        eng = _engine(model).start()
        try:
            req = eng.submit(_prompt(4), 27)
            deadline = time.monotonic() + 60.0
            while not req.tokens_so_far():
                assert time.monotonic() < deadline
                time.sleep(0.01)
            assert eng.kv.used_pages > 0
            req.cancel()
            while eng.kv.used_pages and time.monotonic() < deadline:
                time.sleep(0.01)
            assert eng.kv.used_pages == 0
            assert eng.kv.leak_check() is None
        finally:
            eng.stop()


# -- the degradation ladder --------------------------------------------------

class TestLadder:
    @pytest.mark.faults
    def test_prefill_fault_fails_the_stream_not_the_engine(self, model):
        eng = _engine(model).start()
        try:
            faults.arm("serving.prefill:raise:nth=1")
            req = eng.submit(_prompt(4), 4)
            with pytest.raises(ServingError):
                req.result(60.0)
            assert eng.kv.used_pages == 0  # the failed admit released
            faults.disarm()
            out = np.asarray(eng.generate(_prompt(4), 4, timeout=120.0))
            assert out.shape == (8,)
        finally:
            eng.stop()

    @pytest.mark.faults
    def test_decode_fault_fails_active_and_recovers(self, model):
        eng = _engine(model).start()
        try:
            # warm first so the armed consult hits a real decode step
            eng.generate(_prompt(4), 2, timeout=120.0)
            faults.arm("serving.decode:raise:nth=1")
            req = eng.submit(_prompt(4), 6)
            with pytest.raises(ServingError):
                req.result(60.0)
            assert eng.kv.used_pages == 0
            faults.disarm()
            p = _prompt(5, seed=31)
            np.testing.assert_array_equal(
                np.asarray(eng.generate(p, 5, timeout=120.0)),
                _dense(model, p, 5))
        finally:
            eng.stop()

    @pytest.mark.slow
    def test_watchdog_abort_releases_pages_and_respawns(self, model):
        eng = _engine(model).start()
        try:
            req = eng.submit(_prompt(4), 27)
            deadline = time.monotonic() + 60.0
            while eng.active_streams() == 0:
                assert time.monotonic() < deadline
                time.sleep(0.01)
            eng._on_wedged({"stage": "abort", "iteration": 0})
            with pytest.raises(ServingError, match="wedged"):
                req.result(60.0)
            assert eng.kv.used_pages == 0
            assert eng.kv.leak_check() is None
            # the respawned loop serves the next stream
            p = _prompt(3, seed=40)
            np.testing.assert_array_equal(
                np.asarray(eng.generate(p, 4, timeout=120.0)),
                _dense(model, p, 4))
        finally:
            eng.stop()

    def test_hot_swap_drains_with_zero_dropped_streams(self, model):
        srv = InferenceServer(model)
        eng = GenerationEngine(server=srv,
                               config=GenerationConfig(**CFG)).start()
        try:
            reqs = [eng.submit(_prompt(4, seed=50 + i), 20)
                    for i in range(3)]
            deadline = time.monotonic() + 60.0
            while not any(r.tokens_so_far() for r in reqs):
                assert time.monotonic() < deadline
                time.sleep(0.01)
            new = jax.tree_util.tree_map(
                lambda a: a * 1.001
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                else a,
                srv.model.params)
            assert srv.push_weights(new, source="test")
            for r in reqs:
                out = np.asarray(r.result(120.0))
                assert out.shape == (24,)  # full length: zero drops
                assert r.error is None
        finally:
            eng.stop()
            srv.stop()

    def test_kv_occupancy_feeds_shed_pressure(self, model):
        srv = InferenceServer(model)
        eng = GenerationEngine(server=srv,
                               config=GenerationConfig(**CFG))
        try:
            assert srv.generation_engine is eng
            base = srv.shed_pressure()
            eng.kv.alloc("x", 60)          # ~95% of the pool
            assert srv.shed_pressure() >= eng.kv.occupancy() > base
            eng.kv.release("x")
        finally:
            srv.stop()


# -- bounded program set -----------------------------------------------------

class TestCompileStability:
    def test_zero_fresh_compiles_after_warm_up(self, model):
        from deeplearning4j_tpu.runtime import compile_stats

        eng = _engine(model).start()
        try:
            # warm the step program + the 8- and 16-bucket prefills
            eng.generate(_prompt(4), 3, timeout=120.0)
            eng.generate(_prompt(12), 3, temperature=1.0, seed=1,
                         timeout=120.0)
            snap = compile_stats.snapshot()
            reqs = [
                eng.submit(_prompt(3 + i, seed=60 + i), 4 + i,
                           temperature=float(i % 3) * 0.5,
                           top_k=(i % 4), seed=i)
                for i in range(8)          # all within warmed buckets
            ]
            for r in reqs:
                r.result(120.0)
            delta = compile_stats.snapshot() - snap
            assert delta.fresh_backend_compiles == 0, delta.as_dict()
        finally:
            eng.stop()


# -- prefill/decode disaggregation -------------------------------------------

class TestDisaggregation:
    def test_handoff_between_engines_matches_dense(self, model):
        pre = _engine(model)               # never started: prefill only
        dec = _engine(model).start()
        try:
            p = _prompt(6, seed=70)
            handoff = pre.prefill_detached(p, 10, temperature=1.0, seed=5)
            assert handoff["k"].dtype == np.float32
            out = np.asarray(dec.join_prefilled(handoff).result(120.0))
            np.testing.assert_array_equal(
                out, _dense(model, p, 10, temperature=1.0, seed=5))
        finally:
            dec.stop()

    @pytest.mark.slow
    def test_f32_prefill_feeds_int8_decode(self, model):
        """The handoff crosses the replica boundary in f32 and lands in
        the decode pool's OWN page dtype."""
        pre = _engine(model)
        dec = _engine(model, kv_dtype="int8").start()
        try:
            p = _prompt(5, seed=71)
            out = np.asarray(
                dec.join_prefilled(pre.prefill_detached(p, 8))
                .result(120.0))
            ref = _dense(model, p, 8)
            m = min(len(out), len(ref))
            assert (np.asarray(out[:m]) == ref[:m]).mean() >= 0.8
        finally:
            dec.stop()

    @pytest.mark.slow
    def test_fleet_routes_roles_and_matches_dense(self):
        from deeplearning4j_tpu.serving.fleet import ServingFleet

        def factory():
            return TransformerEncoder(
                vocab_size=VOCAB, d_model=D, n_heads=HEADS,
                n_layers=LAYERS, causal=True, seed=5,
            ).init_model()

        fleet = ServingFleet(
            factory, n_replicas=2, roles=["prefill", "decode"],
            generation_config=GenerationConfig(**CFG),
        ).start()
        try:
            assert [h.role for h in fleet.handles] == ["prefill", "decode"]
            assert fleet.engines["r0"]._thread is None   # no decode loop
            p = _prompt(5, seed=80)
            out = np.asarray(fleet.generate(p, 9, timeout=120.0))
            np.testing.assert_array_equal(
                out, _dense(fleet.replicas[0].model, p, 9))
        finally:
            fleet.stop()

    def test_fleet_roles_must_cover_every_replica(self):
        from deeplearning4j_tpu.serving.fleet import ServingFleet

        with pytest.raises(ValueError, match="roles"):
            ServingFleet(lambda: None, n_replicas=2, roles=["both"])

    def test_router_rejects_when_role_group_empty(self):
        from deeplearning4j_tpu.serving.router import (
            ReplicaHandle, Router,
        )

        class _Stub:
            def health(self):
                return {"status": "serving", "shed_pressure": 0.0,
                        "breaker_state": "closed"}

        h = ReplicaHandle("r0", _Stub(), role="decode")
        router = Router([h])
        assert router.pick_for_role("decode") is h
        with pytest.raises(ServingRejected) as ei:
            router.pick_for_role("prefill")    # nobody serves prefill
        assert ei.value.reason == "no_replicas"
        with pytest.raises(ValueError, match="role"):
            ReplicaHandle("r1", _Stub(), role="oracle")


# -- the HTTP surface --------------------------------------------------------

class TestHTTPGenerate:
    @pytest.fixture()
    def stack(self, model):
        from deeplearning4j_tpu.serving.http import ServingHTTPServer

        srv = InferenceServer(model)
        eng = GenerationEngine(server=srv,
                               config=GenerationConfig(**CFG)).start()
        http = ServingHTTPServer(srv).start()
        yield srv, eng, http
        http.stop()
        eng.stop()
        srv.stop()

    def _post(self, url, payload):
        req = urllib.request.Request(
            url + "v1/generate", json.dumps(payload).encode(),
            {"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=120) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_blocking_generate_matches_dense(self, model, stack):
        _, _, http = stack
        p = _prompt(5, seed=90)
        code, doc = self._post(http.url, {
            "prompt": p.tolist(), "max_new_tokens": 7})
        assert code == 200
        np.testing.assert_array_equal(
            np.asarray(doc["tokens"]), _dense(model, p, 7))
        assert doc["prompt_len"] == 5
        assert doc["ttft_ms"] is not None

    def test_streaming_emits_tokens_then_done(self, model, stack):
        _, _, http = stack
        p = _prompt(4, seed=91)
        req = urllib.request.Request(
            http.url + "v1/generate",
            json.dumps({"prompt": p.tolist(), "max_new_tokens": 6,
                        "stream": True}).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.status == 200
            lines = [json.loads(ln) for ln in r.read().splitlines() if ln]
        assert lines[-1]["done"] is True
        assert lines[-1]["error"] is None
        toks = [ln["token"] for ln in lines[:-1]]
        np.testing.assert_array_equal(
            np.asarray(toks), _dense(model, p, 6)[len(p):])

    def test_over_capacity_and_bad_prompt_are_400(self, stack):
        _, _, http = stack
        code, _ = self._post(http.url, {"prompt": _prompt(8).tolist(),
                                        "max_new_tokens": 40})
        assert code == 400
        code, _ = self._post(http.url, {"prompt": "not tokens"})
        assert code == 400

    def test_replica_without_engine_is_400(self, model):
        from deeplearning4j_tpu.serving.http import ServingHTTPServer

        srv = InferenceServer(model)
        http = ServingHTTPServer(srv).start()
        try:
            code, doc = self._post(http.url, {"prompt": [1, 2]})
            assert code == 400
            assert "engine" in doc["error"]
        finally:
            http.stop()
            srv.stop()

    def test_kv_exhaustion_is_429_over_http(self, model):
        from deeplearning4j_tpu.serving.http import ServingHTTPServer

        srv = InferenceServer(model)
        eng = GenerationEngine(
            server=srv,
            config=GenerationConfig(**{**CFG, "num_pages": 3})).start()
        http = ServingHTTPServer(srv).start()
        try:
            code, doc = self._post(http.url, {
                "prompt": _prompt(17).tolist(), "max_new_tokens": 4})
            assert code == 429
            assert doc["reason"] == "kv_exhausted"
        finally:
            http.stop()
            eng.stop()
            srv.stop()


# -- telemetry ---------------------------------------------------------------

class TestTelemetry:
    def test_token_counter_and_kv_gauges_move(self, model):
        from deeplearning4j_tpu.observe.metrics import registry

        eng = _engine(model).start()
        try:
            before = registry().counter("dl4jtpu_decode_tokens_total").value()
            eng.generate(_prompt(4), 5, timeout=120.0)
            after = registry().counter("dl4jtpu_decode_tokens_total").value()
            assert after >= before + 5
            assert registry().gauge("dl4jtpu_kv_pages_total").value() \
                == CFG["num_pages"] - 1
            st = eng.stats()
            assert st["tokens_generated"] >= 5
            assert st["kv"]["used_pages"] == 0
        finally:
            eng.stop()
