"""Audio ETL: WAV decode round-trips, spectrograms, labeled readers,
and an end-to-end audio-classification train through the bridge."""

import numpy as np
import pytest

from deeplearning4j_tpu.datavec import (
    SpectrogramRecordReader,
    VideoRecordReader,
    WavFileRecordReader,
    read_wav,
    spectrogram,
    write_wav,
)

RATE = 8000


def tone(freq, seconds=0.25, rate=RATE, amp=0.5):
    t = np.arange(int(seconds * rate)) / rate
    return (amp * np.sin(2 * np.pi * freq * t)).astype(np.float32)


@pytest.fixture
def audio_tree(tmp_path):
    """two classes: low tones vs high tones, 4 clips each."""
    for cls, freq in (("low", 220.0), ("high", 1760.0)):
        d = tmp_path / cls
        d.mkdir()
        for i in range(4):
            write_wav(d / f"clip{i}.wav", tone(freq * (1 + 0.02 * i)), RATE)
    return tmp_path


def test_wav_round_trip(tmp_path):
    x = tone(440.0)
    write_wav(tmp_path / "t.wav", x, RATE)
    back, rate = read_wav(tmp_path / "t.wav")
    assert rate == RATE
    np.testing.assert_allclose(back, x, atol=1e-3)


def test_wav_stereo_and_widths(tmp_path):
    import wave

    stereo = np.stack([tone(440.0), tone(880.0)], axis=1)
    write_wav(tmp_path / "s.wav", stereo, RATE)
    back, _ = read_wav(tmp_path / "s.wav")
    assert back.shape == stereo.shape
    np.testing.assert_allclose(back, stereo, atol=1e-3)
    # 8-bit unsigned path
    pcm8 = ((tone(330.0) * 127) + 128).astype(np.uint8)
    with wave.open(str(tmp_path / "u8.wav"), "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(1)
        w.setframerate(RATE)
        w.writeframes(pcm8.tobytes())
    x8, _ = read_wav(tmp_path / "u8.wav")
    np.testing.assert_allclose(x8, tone(330.0), atol=2e-2)


def test_spectrogram_shapes_and_peak():
    x = tone(1000.0, seconds=0.5)
    s = spectrogram(x, frame_length=256, frame_step=128, log=False)
    n_frames = 1 + (len(x) - 256) // 128
    assert s.shape == (n_frames, 129)
    # the 1 kHz bin dominates: bin = 1000/(8000/256) = 32
    assert abs(int(np.argmax(s.mean(axis=0))) - 32) <= 1


def test_wav_reader_labels_and_shapes(audio_tree):
    rr = WavFileRecordReader(clip_samples=2000).initialize(audio_tree)
    assert rr.labels == ["high", "low"]
    recs = list(rr)
    assert len(recs) == 8
    for samples, label in recs:
        assert samples.shape == (2000,)
        assert label in (0, 1)
    assert rr.sample_rate == RATE


def test_wav_reader_pads_short_clips(tmp_path):
    d = tmp_path / "x"
    d.mkdir()
    write_wav(d / "short.wav", tone(440.0, seconds=0.05), RATE)
    rr = WavFileRecordReader(clip_samples=4000).initialize(tmp_path)
    (samples, _), = list(rr)
    assert samples.shape == (4000,)
    assert np.all(samples[500:] == 0.0)


def test_compressed_audio_gated(tmp_path):
    (tmp_path / "a.mp3").write_bytes(b"\xff\xfb\x90\x00")
    with pytest.raises(ValueError, match="PCM WAV only"):
        WavFileRecordReader().initialize(tmp_path)


def test_video_reader_gated():
    with pytest.raises(NotImplementedError, match="video decoding"):
        VideoRecordReader("anything.mp4")


def test_spectrogram_reader_trains_classifier(audio_tree):
    from deeplearning4j_tpu.data import DataSet
    from deeplearning4j_tpu.models import SequentialModel
    from deeplearning4j_tpu.nn import Adam
    from deeplearning4j_tpu.nn.activations import Activation
    from deeplearning4j_tpu.nn.conf import (
        Dense,
        InputType,
        NeuralNetConfiguration,
        OutputLayer,
    )
    from deeplearning4j_tpu.nn.losses import Loss

    rr = SpectrogramRecordReader(
        clip_samples=2000, frame_length=256, frame_step=128
    ).initialize(audio_tree)
    feats, labels = [], []
    for s, l in rr:
        feats.append(s.reshape(-1))
        labels.append(l)
    x = np.stack(feats)
    x = (x - x.mean()) / (x.std() + 1e-6)
    y = np.eye(2, dtype=np.float32)[labels]
    conf = (
        NeuralNetConfiguration.builder()
        .seed(5)
        .updater(Adam(1e-2))
        .list()
        .layer(Dense(n_out=16, activation=Activation.RELU))
        .layer(OutputLayer(n_out=2, loss=Loss.MCXENT,
                           activation=Activation.SOFTMAX))
        .set_input_type(InputType.feed_forward(x.shape[1]))
        .build()
    )
    model = SequentialModel(conf).init()
    model.fit((x, y), epochs=40, batch_size=8)
    assert model.evaluate(DataSet(x, y)).accuracy() == 1.0
