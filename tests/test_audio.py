"""Audio ETL: WAV decode round-trips, spectrograms, labeled readers,
and an end-to-end audio-classification train through the bridge."""

import numpy as np
import pytest

from deeplearning4j_tpu.datavec import (
    SpectrogramRecordReader,
    VideoRecordReader,
    WavFileRecordReader,
    read_wav,
    spectrogram,
    write_wav,
)

RATE = 8000


def tone(freq, seconds=0.25, rate=RATE, amp=0.5):
    t = np.arange(int(seconds * rate)) / rate
    return (amp * np.sin(2 * np.pi * freq * t)).astype(np.float32)


@pytest.fixture
def audio_tree(tmp_path):
    """two classes: low tones vs high tones, 4 clips each."""
    for cls, freq in (("low", 220.0), ("high", 1760.0)):
        d = tmp_path / cls
        d.mkdir()
        for i in range(4):
            write_wav(d / f"clip{i}.wav", tone(freq * (1 + 0.02 * i)), RATE)
    return tmp_path


def test_wav_round_trip(tmp_path):
    x = tone(440.0)
    write_wav(tmp_path / "t.wav", x, RATE)
    back, rate = read_wav(tmp_path / "t.wav")
    assert rate == RATE
    np.testing.assert_allclose(back, x, atol=1e-3)


def test_wav_stereo_and_widths(tmp_path):
    import wave

    stereo = np.stack([tone(440.0), tone(880.0)], axis=1)
    write_wav(tmp_path / "s.wav", stereo, RATE)
    back, _ = read_wav(tmp_path / "s.wav")
    assert back.shape == stereo.shape
    np.testing.assert_allclose(back, stereo, atol=1e-3)
    # 8-bit unsigned path
    pcm8 = ((tone(330.0) * 127) + 128).astype(np.uint8)
    with wave.open(str(tmp_path / "u8.wav"), "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(1)
        w.setframerate(RATE)
        w.writeframes(pcm8.tobytes())
    x8, _ = read_wav(tmp_path / "u8.wav")
    np.testing.assert_allclose(x8, tone(330.0), atol=2e-2)


def test_spectrogram_shapes_and_peak():
    x = tone(1000.0, seconds=0.5)
    s = spectrogram(x, frame_length=256, frame_step=128, log=False)
    n_frames = 1 + (len(x) - 256) // 128
    assert s.shape == (n_frames, 129)
    # the 1 kHz bin dominates: bin = 1000/(8000/256) = 32
    assert abs(int(np.argmax(s.mean(axis=0))) - 32) <= 1


def test_wav_reader_labels_and_shapes(audio_tree):
    rr = WavFileRecordReader(clip_samples=2000).initialize(audio_tree)
    assert rr.labels == ["high", "low"]
    recs = list(rr)
    assert len(recs) == 8
    for samples, label in recs:
        assert samples.shape == (2000,)
        assert label in (0, 1)
    assert rr.sample_rate == RATE


def test_wav_reader_pads_short_clips(tmp_path):
    d = tmp_path / "x"
    d.mkdir()
    write_wav(d / "short.wav", tone(440.0, seconds=0.05), RATE)
    rr = WavFileRecordReader(clip_samples=4000).initialize(tmp_path)
    (samples, _), = list(rr)
    assert samples.shape == (4000,)
    assert np.all(samples[500:] == 0.0)


def test_compressed_audio_gated(tmp_path):
    (tmp_path / "a.mp3").write_bytes(b"\xff\xfb\x90\x00")
    with pytest.raises(ValueError, match="PCM WAV only"):
        WavFileRecordReader().initialize(tmp_path)


class TestVideoReader:
    """MJPEG-AVI video decoding without FFmpeg (datavec.video)."""

    def _write_tree(self, root):
        from deeplearning4j_tpu.datavec.video import write_mjpeg_avi

        rng = np.random.default_rng(0)
        for label, base in (("walk", 40), ("run", 200)):
            d = root / label
            d.mkdir()
            for i in range(2):
                # class-distinct brightness so a consumer could classify
                frames = np.clip(
                    rng.normal(base, 10, (5, 24, 32, 3)), 0, 255
                ).astype(np.uint8)
                write_mjpeg_avi(d / f"{i}.avi", frames, fps=10)

    def test_roundtrip_and_labels(self, tmp_path):
        self._write_tree(tmp_path)
        rr = VideoRecordReader(12, 16, 3).initialize(tmp_path)
        assert rr.labels == ["run", "walk"]
        assert rr.num_videos() == 4
        recs = list(rr)
        assert len(recs) == 4
        frames, label = recs[0]
        assert frames.shape == (5, 12, 16, 3)
        assert label in (0, 1)
        # brightness separates the classes through the JPEG round trip
        means = {lab: [] for lab in (0, 1)}
        for f, lab in recs:
            means[lab].append(f.mean())
        assert abs(np.mean(means[0]) - np.mean(means[1])) > 50

    def test_max_frames_and_grayscale(self, tmp_path):
        self._write_tree(tmp_path)
        rr = VideoRecordReader(8, 8, 1, max_frames=3).initialize(tmp_path)
        frames, _ = next(iter(rr))
        assert frames.shape == (3, 8, 8, 1)

    def test_non_mjpeg_stream_raises(self, tmp_path):
        import struct

        # hand-build an AVI whose video chunk is NOT JPEG
        payload = b"00dc" + struct.pack("<I", 4) + b"\x00\x01\x02\x03"
        movi = b"LIST" + struct.pack("<I", 4 + len(payload)) + b"movi" + payload
        body = b"AVI " + movi
        p = tmp_path / "raw.avi"
        p.write_bytes(b"RIFF" + struct.pack("<I", len(body)) + body)
        from deeplearning4j_tpu.datavec.video import read_avi_frames

        with pytest.raises(NotImplementedError, match="MJPEG"):
            read_avi_frames(p, 8, 8)

    def test_non_avi_video_tree_gives_codec_advice(self, tmp_path):
        (tmp_path / "clips").mkdir()
        (tmp_path / "clips" / "a.mp4").write_bytes(b"\x00" * 16)
        with pytest.raises(NotImplementedError, match="MJPEG"):
            VideoRecordReader(8, 8).initialize(tmp_path)

    def test_uppercase_extension_found(self, tmp_path):
        from deeplearning4j_tpu.datavec.video import write_mjpeg_avi

        d = tmp_path / "c"
        d.mkdir()
        write_mjpeg_avi(d / "X.AVI", np.zeros((2, 8, 8, 3), np.uint8))
        rr = VideoRecordReader(8, 8).initialize(tmp_path)
        assert rr.num_videos() == 1

    def test_non_avi_rejected(self, tmp_path):
        p = tmp_path / "x.avi"
        p.write_bytes(b"not an avi at all")
        from deeplearning4j_tpu.datavec.video import read_avi_frames

        with pytest.raises(ValueError, match="not an AVI"):
            read_avi_frames(p, 8, 8)


def test_spectrogram_reader_trains_classifier(audio_tree):
    from deeplearning4j_tpu.data import DataSet
    from deeplearning4j_tpu.models import SequentialModel
    from deeplearning4j_tpu.nn import Adam
    from deeplearning4j_tpu.nn.activations import Activation
    from deeplearning4j_tpu.nn.conf import (
        Dense,
        InputType,
        NeuralNetConfiguration,
        OutputLayer,
    )
    from deeplearning4j_tpu.nn.losses import Loss

    rr = SpectrogramRecordReader(
        clip_samples=2000, frame_length=256, frame_step=128
    ).initialize(audio_tree)
    feats, labels = [], []
    for s, l in rr:
        feats.append(s.reshape(-1))
        labels.append(l)
    x = np.stack(feats)
    x = (x - x.mean()) / (x.std() + 1e-6)
    y = np.eye(2, dtype=np.float32)[labels]
    conf = (
        NeuralNetConfiguration.builder()
        .seed(5)
        .updater(Adam(1e-2))
        .list()
        .layer(Dense(n_out=16, activation=Activation.RELU))
        .layer(OutputLayer(n_out=2, loss=Loss.MCXENT,
                           activation=Activation.SOFTMAX))
        .set_input_type(InputType.feed_forward(x.shape[1]))
        .build()
    )
    model = SequentialModel(conf).init()
    model.fit((x, y), epochs=40, batch_size=8)
    assert model.evaluate(DataSet(x, y)).accuracy() == 1.0
