"""ZeRO-2 persistently sharded gradients (distribute(zero=2),
parallel/zero.py Zero2Placement).

The contract under test: the step's gradients are reduce-scattered ONCE
into a persistent sharded accumulator (grad state bytes/replica ~ 1/n),
the optax step runs per-shard against it, params are all-gathered, and
the accumulator returns zeroed — numerics exactly the replicated DP
epilogue's (the same 1-ulp layout tolerance ZeRO-1's parity suite
established).  Checkpoints persist only the inner optax state (the
accumulator is zeros at every step boundary by construction), so the
on-disk format is unchanged across zero stages.
"""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.data import DataSet, NumpyDataSetIterator
from deeplearning4j_tpu.models import SequentialModel
from deeplearning4j_tpu.nn import Adam
from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    Dense,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.parallel import ParallelConfig, distribute
from deeplearning4j_tpu.parallel import zero as zmod
from deeplearning4j_tpu.runtime.mesh import DATA_AXIS

N_DEV = 8
IN = 8


def two_class_data(n=256, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, IN)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(axis=1) > 0).astype(int)]
    return x, y


def mlp_conf(seed=9):
    return (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Adam(1e-2))
        .activation(Activation.RELU)
        .list()
        .layer(Dense(n_out=32))
        .layer(Dense(n_out=32))
        .layer(OutputLayer(n_out=2, loss=Loss.MCXENT,
                           activation=Activation.SOFTMAX))
        .set_input_type(InputType.feed_forward(IN))
        .build()
    )


def params_exact(a, b, atol=1e-6):
    """ZeRO-2's parity bar: the replicated trajectory to within XLA's
    layout-reassociation ulp (the same bar test_zero1 holds ZeRO-1 to,
    tightened — measured max diff is 1 f32 ulp ~ 6e-8)."""
    for lname in a:
        for pname in a[lname]:
            np.testing.assert_allclose(
                np.asarray(a[lname][pname]), np.asarray(b[lname][pname]),
                rtol=0, atol=atol, err_msg=f"{lname}/{pname}",
            )


def grad_accum_specs(model):
    _, acc = zmod.unwrap_opt_state(model.opt_state)
    assert acc is not None
    return {
        str(leaf.sharding.spec) for leaf in jax.tree.leaves(acc)
    }


@pytest.mark.plan
class TestNumericsParity:
    def test_zero2_matches_replicated_across_fit_evaluate(self):
        """Same seed, same feed, interleaved fit/evaluate: the ZeRO-2
        trajectory is the replicated one to 1 ulp, and evaluate()
        (replicated params path) agrees."""
        x, y = two_class_data(256)
        it = lambda s: NumpyDataSetIterator(x, y, batch_size=64, seed=s)

        rep = SequentialModel(mlp_conf()).init()
        distribute(rep, ParallelConfig(data=N_DEV, zero=0))
        z2 = SequentialModel(mlp_conf()).init()
        distribute(z2, ParallelConfig(data=N_DEV, zero=2))

        rep.fit(it(3), epochs=2)
        z2.fit(it(3), epochs=2)
        params_exact(rep.params, z2.params)

        acc_rep = rep.evaluate(DataSet(x, y)).accuracy()
        acc_z2 = z2.evaluate(DataSet(x, y)).accuracy()
        assert acc_rep == pytest.approx(acc_z2, abs=0.02)

        rep.fit(it(5), epochs=1)
        z2.fit(it(5), epochs=1)
        params_exact(rep.params, z2.params)

    def test_zero2_matches_single_device(self):
        x, y = two_class_data(256)
        it = lambda s: NumpyDataSetIterator(x, y, batch_size=64, seed=s)
        single = SequentialModel(mlp_conf()).init()
        single.fit(it(3), epochs=3)
        z2 = SequentialModel(mlp_conf()).init()
        distribute(z2, ParallelConfig(data=N_DEV, zero=2))
        z2.fit(it(3), epochs=3)
        params_exact(single.params, z2.params)

    def test_grad_accum_microbatches_allclose(self):
        """grad_accum=m>1 scans m microbatches with the accumulation
        SHARDED in the carry; the partial-sum reorder makes parity
        allclose (f32 tolerance), not bitwise — documented."""
        x, y = two_class_data(256)
        it = lambda s: NumpyDataSetIterator(x, y, batch_size=64, seed=s)
        rep = SequentialModel(mlp_conf()).init()
        distribute(rep, ParallelConfig(data=N_DEV, zero=0))
        za = SequentialModel(mlp_conf()).init()
        distribute(za, ParallelConfig(data=N_DEV, zero=2, grad_accum=2))
        rep.fit(it(3), epochs=2)
        za.fit(it(3), epochs=2)
        for lname in rep.params:
            for pname in rep.params[lname]:
                np.testing.assert_allclose(
                    np.asarray(rep.params[lname][pname]),
                    np.asarray(za.params[lname][pname]),
                    rtol=2e-4, atol=2e-5, err_msg=f"{lname}/{pname}",
                )

    def test_grad_accum_draws_distinct_dropout_noise_per_microbatch(self):
        """The accumulation scan folds the microbatch index into the
        step rng — a dropout model's m>1 gradients must NOT reuse one
        mask m times (which would leave the trajectory exactly equal
        to a half-batch run's doubled noise, not the full batch's)."""
        from deeplearning4j_tpu.nn.conf import Dropout

        def dconf(seed=9):
            return (
                NeuralNetConfiguration.builder()
                .seed(seed)
                .updater(Adam(1e-2))
                .activation(Activation.RELU)
                .list()
                .layer(Dense(n_out=32))
                .layer(Dropout(0.5))
                .layer(OutputLayer(n_out=2, loss=Loss.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(IN))
                .build()
            )

        x, y = two_class_data(64)
        # identical FEATURES in both halves of the batch: with a shared
        # mask the two microbatches' dropout draws would coincide, with
        # the fix they differ — observable through the param delta
        xx = np.concatenate([x[:32], x[:32]])
        yy = np.concatenate([y[:32], y[:32]])

        def one_step(m):
            distribute(m, ParallelConfig(data=N_DEV, zero=2,
                                         grad_accum=2))
            m.fit_batch(DataSet(xx, yy))
            return m

        za = one_step(SequentialModel(dconf()).init())
        # reference: same model, same data, but the two microbatches
        # collapsed into one (grad_accum=1) — same rng root.  If the
        # scan reused ONE mask for both microbatches, the accumulated
        # gradient would equal the microbatch gradient (identical
        # halves + identical masks), making the two runs' first-layer
        # updates coincide; distinct per-microbatch masks break the tie
        zb = SequentialModel(dconf()).init()
        distribute(zb, ParallelConfig(data=N_DEV, zero=2, grad_accum=2))
        zb.fit_batch(DataSet(np.concatenate([x[:32], x[:32]]),
                             np.concatenate([y[:32], y[:32]])))
        # determinism sanity: identical runs agree exactly
        for a, b in zip(jax.tree.leaves(za.params),
                        jax.tree.leaves(zb.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # and the per-microbatch keys actually differ: fold(rng, 0)
        # vs fold(rng, 1) must not produce the same dropout pattern —
        # compare against a single-microbatch half-batch step, which
        # WOULD match if the scan reused one mask over identical halves
        zc = SequentialModel(dconf()).init()
        distribute(zc, ParallelConfig(data=N_DEV, zero=2))
        zc.fit_batch(DataSet(x[:32], y[:32]))
        diff = max(
            float(np.abs(np.asarray(a) - np.asarray(c)).max())
            for a, c in zip(jax.tree.leaves(za.params),
                            jax.tree.leaves(zc.params))
        )
        assert diff > 1e-7, (
            "accumulated run equals the single-microbatch run — the "
            "scan is reusing one dropout mask across microbatches"
        )

    def test_grad_accum_requires_zero2(self):
        m = SequentialModel(mlp_conf()).init()
        with pytest.raises(ValueError, match="zero=2"):
            distribute(m, ParallelConfig(data=N_DEV, zero=1,
                                         grad_accum=2))

    def test_grad_accum_rejected_on_recurrent_stacks(self):
        """The accumulation scan lives in the single-batch no-carries
        step; a recurrent/TBPTT model must be told the knob would be a
        silent no-op instead of quietly not splitting."""
        from deeplearning4j_tpu.nn.conf import LSTM, RnnOutputLayer

        conf = (
            NeuralNetConfiguration.builder()
            .seed(9)
            .updater(Adam(1e-2))
            .list()
            .layer(LSTM(n_out=8))
            .layer(RnnOutputLayer(n_out=2, loss=Loss.MCXENT,
                                  activation=Activation.SOFTMAX))
            .set_input_type(InputType.recurrent(IN, 16))
            .build()
        )
        m = SequentialModel(conf).init()
        with pytest.raises(NotImplementedError, match="accumulation"):
            distribute(m, ParallelConfig(data=N_DEV, zero=2,
                                         grad_accum=2))
        # zero=2 WITHOUT accumulation still distributes fine
        m2 = SequentialModel(conf).init()
        distribute(m2, ParallelConfig(data=N_DEV, zero=2))
        assert zmod.is_wrapped(m2.opt_state)

    def test_indivisible_accum_batch_raises_actionably(self):
        m = SequentialModel(mlp_conf()).init()
        distribute(m, ParallelConfig(data=N_DEV, zero=2, grad_accum=3))
        x, y = two_class_data(64)
        with pytest.raises(ValueError, match="divisible"):
            m.fit_batch(DataSet(x, y))       # 64 % 3 != 0


@pytest.mark.plan
class TestGradStateResidency:
    def test_accumulator_sharded_and_bytes_one_nth(self):
        z2 = SequentialModel(mlp_conf()).init()
        distribute(z2, ParallelConfig(data=N_DEV, zero=2))
        assert any(DATA_AXIS in s for s in grad_accum_specs(z2))
        rep = SequentialModel(mlp_conf()).init()
        distribute(rep, ParallelConfig(data=N_DEV, zero=0))
        g2 = zmod.grad_state_bytes_per_replica(z2)
        grep = zmod.grad_state_bytes_per_replica(rep)
        # ~1/n with a small replicated remainder (ragged leaves)
        assert g2 < 1.5 * grep / N_DEV + 4096
        # opt state shards too (inner counted, accumulator excluded)
        o2 = zmod.opt_state_bytes_per_replica(z2.opt_state)
        orep = zmod.opt_state_bytes_per_replica(rep.opt_state)
        assert o2 < 1.5 * orep / N_DEV + 4096

    def test_grad_state_stays_sharded_through_training(self):
        x, y = two_class_data(128)
        z2 = SequentialModel(mlp_conf()).init()
        distribute(z2, ParallelConfig(data=N_DEV, zero=2))
        b0 = zmod.grad_state_bytes_per_replica(z2)
        z2.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=1),
               epochs=1)
        assert any(DATA_AXIS in s for s in grad_accum_specs(z2))
        assert zmod.grad_state_bytes_per_replica(z2) == b0
        # the accumulator is zeros at every step boundary
        _, acc = zmod.unwrap_opt_state(z2.opt_state)
        for leaf in jax.tree.leaves(acc):
            assert not np.asarray(leaf).any()

    def test_gauges_carry_zero2_mode(self):
        from deeplearning4j_tpu.observe.metrics import registry

        z2 = SequentialModel(mlp_conf()).init()
        distribute(z2, ParallelConfig(data=N_DEV, zero=2))
        reg = registry()
        assert reg.gauge("dl4jtpu_opt_state_bytes").value(
            mode="zero2"
        ) == zmod.opt_state_bytes_per_replica(z2.opt_state)
        assert reg.gauge("dl4jtpu_grad_state_bytes").value(
            mode="zero2"
        ) == zmod.grad_state_bytes_per_replica(z2)

    def test_step_programs_registered_with_zero2_marker(self):
        from deeplearning4j_tpu.observe import cost

        z2 = SequentialModel(mlp_conf()).init()
        distribute(z2, ParallelConfig(data=N_DEV, zero=2))
        x, y = two_class_data(64)
        z2.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=1),
               epochs=1)
        assert any("zero2x1" in str(k) for k in z2._step_fns)
        recs = [r for r in cost.registry().programs()
                if r.owner_ref() is z2 and r.kind.startswith("train")]
        assert recs and all("zero2" in str(r.key) for r in recs)

    def test_redistribute_unwraps(self):
        """zero=2 -> zero=0 re-distribution drops the wrapper; the
        optimizer state round-trips unchanged."""
        m = SequentialModel(mlp_conf()).init()
        distribute(m, ParallelConfig(data=N_DEV, zero=2))
        assert zmod.is_wrapped(m.opt_state)
        distribute(m, ParallelConfig(data=N_DEV, zero=0))
        assert not zmod.is_wrapped(m.opt_state)
        assert m._zero_placement is None
        distribute(m, ParallelConfig(data=N_DEV, zero=1))
        assert not zmod.is_wrapped(m.opt_state)
        assert m._zero_placement is not None


@pytest.mark.plan
class TestCheckpointRoundTrip:
    def test_save_restore_resume_matches_uninterrupted(self, tmp_path):
        """save -> restore -> distribute(zero=2) -> resume: trajectory
        matches the uninterrupted ZeRO-2 run, and the checkpoint holds
        the INNER optax state only (format unchanged across stages)."""
        from deeplearning4j_tpu.train.checkpoint import ModelSerializer

        x, y = two_class_data(128)
        it = lambda s: NumpyDataSetIterator(x, y, batch_size=64, seed=s)

        z2 = SequentialModel(mlp_conf()).init()
        distribute(z2, ParallelConfig(data=N_DEV, zero=2))
        z2.fit(it(3), epochs=1)
        path = str(tmp_path / "zero2.zip")
        ModelSerializer.write_model(z2, path)

        restored = ModelSerializer.restore(path)
        # the restored (host) opt state is UNWRAPPED — same leaf set a
        # zero=0/1 checkpoint carries
        assert not zmod.is_wrapped(restored.opt_state)
        distribute(restored, ParallelConfig(data=N_DEV, zero=2))
        assert zmod.is_wrapped(restored.opt_state)
        restored.fit(it(5), epochs=1)
        z2.fit(it(5), epochs=1)
        params_exact(z2.params, restored.params)

    def test_zero2_checkpoint_restores_into_replicated_model(self, tmp_path):
        """Cross-stage restore: a zero=2 checkpoint feeds a zero=0
        model (and vice versa would too) — the format is stage-free."""
        from deeplearning4j_tpu.train.checkpoint import ModelSerializer

        x, y = two_class_data(128)
        z2 = SequentialModel(mlp_conf()).init()
        distribute(z2, ParallelConfig(data=N_DEV, zero=2))
        z2.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=3),
               epochs=1)
        path = str(tmp_path / "x.zip")
        ModelSerializer.write_model(z2, path)
        restored = ModelSerializer.restore(path)
        distribute(restored, ParallelConfig(data=N_DEV, zero=0))
        restored.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=5),
                     epochs=1)
        assert np.isfinite(restored.score_value)

    def test_recovery_rollback_rewraps_and_replaces(self, tmp_path):
        """RecoveryPolicy._install on a zero=2 model: the restored
        INNER state is re-wrapped (fresh zero accumulator) and placed
        onto the recorded shardings — training continues sharded."""
        from deeplearning4j_tpu.train.checkpoint import ModelSerializer
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        x, y = two_class_data(128)
        z2 = SequentialModel(mlp_conf()).init()
        distribute(z2, ParallelConfig(data=N_DEV, zero=2))
        z2.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=3),
               epochs=1)
        path = str(tmp_path / "ck.zip")
        ModelSerializer.write_model(z2, path)

        restored = ModelSerializer.restore(path)     # host, unwrapped
        RecoveryPolicy._install(z2, restored)
        assert zmod.is_wrapped(z2.opt_state)
        assert any(DATA_AXIS in s for s in grad_accum_specs(z2))
        z2.fit(NumpyDataSetIterator(x, y, batch_size=64, seed=5),
               epochs=1)
        assert np.isfinite(z2.score_value)
