"""Ring / Ulysses attention vs dense reference — exactness tests on a real
multi-device CPU mesh (the §4.2 multi-node-without-a-cluster pattern)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.ops.attention import mha, ring_attention, ulysses_attention
from deeplearning4j_tpu.runtime.mesh import MeshSpec, make_mesh, shard_map

B, T, H, D = 2, 32, 4, 8
NSEQ = 4


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh(MeshSpec.of(seq=NSEQ), jax.devices()[:NSEQ])


def qkv(seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(0, 1, (B, T, H, D)).astype(np.float32)) for _ in range(3)
    )


def _sharded(fn, mesh, with_mask):
    in_specs = (P(None, "seq"), P(None, "seq"), P(None, "seq"))
    if with_mask:
        in_specs = in_specs + (P(None, "seq"),)
    return jax.jit(
        # check_vma=False matches how the layers invoke these kernels
        # (legacy check_rep miscounts the ring scan's carry in reverse)
        shard_map(fn, mesh=mesh, in_specs=in_specs,
                  out_specs=P(None, "seq"), check_vma=False)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(seq_mesh, causal):
    q, k, v = qkv()
    dense = mha(q, k, v, causal=causal)
    ring = _sharded(
        functools.partial(ring_attention, axis="seq", causal=causal), seq_mesh, False
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(seq_mesh, causal):
    q, k, v = qkv(1)
    dense = mha(q, k, v, causal=causal)
    uly = _sharded(
        functools.partial(ulysses_attention, axis="seq", causal=causal), seq_mesh, False
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(dense), rtol=2e-4, atol=2e-5)


def test_ring_with_key_mask(seq_mesh):
    q, k, v = qkv(2)
    mask = jnp.asarray(
        (np.arange(T)[None, :] < np.array([[20], [9]])).astype(np.float32)
    )
    dense = mha(q, k, v, mask=mask)
    ring = _sharded(
        lambda q, k, v, m: ring_attention(q, k, v, axis="seq", mask=m), seq_mesh, True
    )(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-4, atol=2e-5)


def test_ulysses_with_key_mask(seq_mesh):
    q, k, v = qkv(3)
    mask = jnp.asarray(
        (np.arange(T)[None, :] < np.array([[16], [28]])).astype(np.float32)
    )
    dense = mha(q, k, v, mask=mask)
    uly = _sharded(
        lambda q, k, v, m: ulysses_attention(q, k, v, axis="seq", mask=m), seq_mesh, True
    )(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(dense), rtol=2e-4, atol=2e-5)


def test_ring_gradients_match_dense(seq_mesh):
    q, k, v = qkv(4)

    def loss_dense(q, k, v):
        return jnp.sum(mha(q, k, v, causal=True) ** 2)

    ring_fn = _sharded(
        functools.partial(ring_attention, axis="seq", causal=True), seq_mesh, False
    )

    def loss_ring(q, k, v):
        return jnp.sum(ring_fn(q, k, v) ** 2)

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4)


def test_fully_masked_rows_are_zero():
    q, k, v = qkv(5)
    mask = jnp.zeros((B, T), jnp.float32)  # everything masked
    out = mha(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)
