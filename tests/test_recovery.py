"""Self-healing training (ISSUE 6): the step watchdog notices wedged
steps and escalates warn -> stack dump -> abort; `RecoveryPolicy` rolls
a diverged model back to the pinned last-good checkpoint with LR
backoff and a skip-window, splits OOM'd batches into microbatches, and
quarantines poison batches instead of dying.  Everything is provoked
deterministically through `runtime.faults` (new sites ``device.sync``
and ``data.decode``) or injected fakes; no sleep exceeds 0.5s.
"""

import glob
import os
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.runtime import faults
from deeplearning4j_tpu.runtime.watchdog import (
    EXIT_STEP_WEDGED,
    STAGES,
    StepWatchdog,
)

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _disarm():
    """Never leak an armed plan into the next test."""
    yield
    faults.disarm()


def _model(seed=3, n_in=4, n_out=2):
    from deeplearning4j_tpu.models import SequentialModel
    from deeplearning4j_tpu.nn.conf import (
        Dense, InputType, NeuralNetConfiguration, OutputLayer,
    )

    conf = (
        NeuralNetConfiguration.builder().seed(seed).list()
        .layer(Dense(n_out=8)).layer(OutputLayer(n_out=n_out))
        .set_input_type(InputType.feed_forward(n_in)).build()
    )
    return SequentialModel(conf).init()


def _feed(n=10, batch=8, n_in=4, n_out=2, seed=0):
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterator import DataSetIterator

    class Feed(DataSetIterator):
        def reset(self):
            pass

        def __iter__(self):
            rng = np.random.default_rng(seed)
            for _ in range(n):
                x = rng.normal(size=(batch, n_in)).astype(np.float32)
                y = np.eye(n_out, dtype=np.float32)[
                    rng.integers(0, n_out, batch)
                ]
                yield DataSet(x, y)

    return Feed()


def _saver(store, every=4):
    from deeplearning4j_tpu.train.listeners import TrainingListener

    class Saver(TrainingListener):
        def iteration_done(self, model, iteration, epoch, score):
            if iteration and iteration % every == 0:
                store.save(model, step=iteration)

    return Saver()


def _counter(name, **labels):
    from deeplearning4j_tpu.observe.metrics import registry

    return registry().counter(name).value(**labels)


def _read(path):
    with open(path) as f:
        return f.read()


# -- StepWatchdog unit (fake clock, no monitor thread) ----------------------

class TestStepWatchdogUnit:
    def _wd(self, **kw):
        self.now = [0.0]
        kw.setdefault("clock", lambda: self.now[0])
        kw.setdefault("threaded", False)
        return StepWatchdog(**kw)

    def test_deadline_is_cold_floor_without_ewma_then_k_times_ewma(self):
        wd = self._wd(floor_s=1.0, cold_floor_s=100.0, k=10.0)
        assert wd.deadline_s() == 100.0
        wd.arm(0)
        self.now[0] = 2.0
        wd.disarm(2.0)                      # first sample: ewma = 2.0
        assert wd.ewma == 2.0
        assert wd.deadline_s() == 20.0      # k * ewma > floor
        wd.arm(1)
        self.now[0] = 2.1
        wd.disarm(0.0)                      # decays toward 0
        assert wd.deadline_s() == max(1.0, 10.0 * wd.ewma)

    def test_failed_steps_do_not_feed_the_ewma(self):
        wd = self._wd()
        wd.arm(0)
        wd.disarm(None)
        assert wd.ewma is None

    def test_escalation_ladder_warn_dump_abort(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DL4JTPU_CRASH_DIR", str(tmp_path))
        aborts = []
        wd = self._wd(floor_s=1.0, cold_floor_s=1.0, k=10.0,
                      dump_after=2.0, abort_after=3.0, abort=aborts.append)
        wd.arm(7, n_steps=1)
        wd.poll()
        assert wd.events == []              # nothing due yet
        self.now[0] = 1.01
        wd.poll()
        assert [e["stage"] for e in wd.events] == ["warn"]
        self.now[0] = 2.01
        wd.poll()
        assert [e["stage"] for e in wd.events] == ["warn", "stack_dump"]
        assert wd.report_paths and os.path.exists(wd.report_paths[0])
        text = _read(wd.report_paths[0])
        assert "threads (" in text and "iteration: 7" in text
        self.now[0] = 3.01
        wd.poll()
        assert [e["stage"] for e in wd.events] == list(STAGES)
        assert aborts and aborts[0]["iteration"] == 7

    def test_escalated_steps_do_not_feed_the_ewma(self):
        wd = self._wd(floor_s=1.0, cold_floor_s=1.0)
        wd.arm(0)
        self.now[0] = 1.01
        wd.poll()                       # warn fired: the step stalled
        assert [e["stage"] for e in wd.events] == ["warn"]
        self.now[0] = 1.2
        wd.disarm(1.2)                  # completed AFTER escalating
        # a stall folded into the EWMA would inflate every later
        # deadline by ~k x the stall, masking the next genuine wedge
        assert wd.ewma is None

    def test_disarm_cancels_pending_escalation(self):
        aborts = []
        wd = self._wd(floor_s=1.0, cold_floor_s=1.0, abort=aborts.append)
        wd.arm(0)
        wd.disarm(0.5)
        self.now[0] = 100.0
        wd.poll()
        assert wd.events == [] and not aborts

    def test_raising_abort_does_not_kill_the_shared_monitor(
        self, tmp_path, monkeypatch
    ):
        import sys

        monkeypatch.setenv("DL4JTPU_CRASH_DIR", str(tmp_path))

        def bad_abort(event):
            sys.exit(25)    # SystemExit off the main thread

        wd = StepWatchdog(floor_s=0.02, cold_floor_s=0.02,
                          dump_after=1.5, abort_after=2.0, abort=bad_abort)
        wd.arm(0)
        deadline = time.monotonic() + 5.0
        while (not wd.events or wd.events[-1]["stage"] != "abort"):
            assert time.monotonic() < deadline, wd.events
            time.sleep(0.01)
        wd.disarm(None)
        # the monitor must survive the raising action and keep serving
        # every watchdog in the process
        assert wd._mon.is_alive()
        wd2 = StepWatchdog(floor_s=0.02, cold_floor_s=0.02)
        assert wd2._mon is wd._mon
        wd2.arm(1)
        deadline = time.monotonic() + 5.0
        while not wd2.events:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        wd2.disarm(None)

    def test_grouped_programs_scale_the_deadline_by_n_steps(self):
        wd = self._wd(floor_s=0.1, cold_floor_s=0.1, k=10.0)
        wd.arm(0)
        self.now[0] = 0.4
        wd.disarm(0.4)                      # ewma 0.4/step
        wd.arm(1, n_steps=8)                # deadline 10 * 0.4 * 8 = 32
        self.now[0] = 20.0
        wd.poll()
        assert wd.events == []
        wd.disarm(None)


# -- hang injection through the real fit loop -------------------------------

class TestWatchdogHangInjection:
    def test_injected_device_sync_hang_fires_within_deadline(
        self, tmp_path, monkeypatch
    ):
        """device.sync delay 0.4s vs a 0.05s deadline: the watchdog
        (real monitor thread) must warn AND write the thread-stack dump
        while the step is still wedged."""
        monkeypatch.setenv("DL4JTPU_CRASH_DIR", str(tmp_path))
        m = _model()
        m._watchdog = StepWatchdog(floor_s=0.05, cold_floor_s=0.05, k=10.0)
        warns_before = _counter("dl4jtpu_watchdog_stalls_total", stage="warn")
        faults.arm("device.sync:delay:nth=2,secs=0.45")
        m.fit(_feed(4), epochs=1)
        faults.disarm()
        wd = m._watchdog
        stages = [e["stage"] for e in wd.events]
        assert "warn" in stages and "stack_dump" in stages
        # fired within the wedged window, not after the step returned
        assert all(e["stalled_s"] < 0.45 for e in wd.events)
        reports = glob.glob(str(tmp_path / "dl4jtpu-hang-report-*"))
        assert reports and wd.report_paths
        report_text = _read(reports[0])
        assert "device_sync" in report_text or "maybe_fail" in report_text
        assert _counter(
            "dl4jtpu_watchdog_stalls_total", stage="warn"
        ) >= warns_before + 1
        # training completed despite the stall (no abort configured)
        assert m.iteration == 4

    def test_fit_with_empty_plan_leaves_watchdog_silent(self):
        m = _model()
        m.fit(_feed(6), epochs=1)
        assert m._watchdog is not None      # created by default flags
        assert m._watchdog.events == []
        assert m._watchdog.ewma is not None  # fed by every step


# -- quarantine store --------------------------------------------------------

class TestQuarantineStore:
    def test_roundtrip_bytes_and_metadata(self, tmp_path):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.quarantine import QuarantineStore

        q = QuarantineStore(str(tmp_path), cap=4)
        ds = DataSet(np.full((2, 3), np.nan, np.float32),
                     np.ones((2, 2), np.float32))
        path = q.put("nonfinite_input", batch=ds)
        assert path and os.path.exists(path)
        [rec] = q.entries()
        assert rec["reason"] == "nonfinite_input" and rec["has_bytes"]
        loaded = np.load(path.replace(".json", ".npz"))
        assert np.isnan(loaded["features"]).all()
        assert loaded["labels"].shape == (2, 2)

    def test_cap_bounds_disk_and_survives_restart(self, tmp_path):
        from deeplearning4j_tpu.data.quarantine import QuarantineStore

        q = QuarantineStore(str(tmp_path), cap=2)
        assert q.put("decode_error", error=ValueError("x"))
        assert q.put("decode_error", error=ValueError("y"))
        assert q.put("decode_error") is None       # full
        # a fresh store over the same dir inherits the spent budget
        q2 = QuarantineStore(str(tmp_path), cap=2)
        assert q2.full and q2.put("decode_error") is None
        assert len(q2.entries()) == 2


# -- checkpoint pinning ------------------------------------------------------

class TestCheckpointPinning:
    def test_gc_never_collects_the_pinned_rollback_target(self, tmp_path):
        from deeplearning4j_tpu.train.checkpoint import CheckpointStore

        store = CheckpointStore(str(tmp_path), keep_last=2)
        m = _model()
        for step in (1, 2, 3, 4, 5):
            store.save(m, step=step)
        assert store.all_steps() == [4, 5]          # plain rotation
        store.pin(4)
        for step in (6, 7, 8):
            store.save(m, step=step)
        assert store.all_steps() == [4, 7, 8]       # pinned survives
        store.unpin(4)
        store.gc()
        assert store.all_steps() == [7, 8]

    def test_policy_pins_its_rollback_target_through_rotation(self, tmp_path):
        from deeplearning4j_tpu.train.checkpoint import CheckpointStore
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        store = CheckpointStore(str(tmp_path / "ck"), keep_last=1)
        m = _model()
        store.save(m, step=2)
        policy = RecoveryPolicy(store).attach(m)
        assert store.pinned_steps() == {2}
        # verified saves ADVANCE the pin: last-good tracks the newest
        # checkpoint that PROVES intact, not the attach-time snapshot
        store.save(m, step=3)
        assert store.pinned_steps() == {3}
        # torn saves do NOT advance it — and the pinned good file
        # survives keep_last=1 rotation while corrupt ones rotate through
        from deeplearning4j_tpu.runtime import faults

        faults.arm("checkpoint.write:truncate:every=1")
        try:
            for step in (4, 5):
                store.save(m, step=step)
        finally:
            faults.disarm()
        assert store.pinned_steps() == {3}
        assert 3 in store.all_steps()               # survives keep_last=1
        entry = store.latest_valid()
        assert entry is not None and entry["step"] == 3
        policy.detach(m)
        store.gc()
        assert 3 not in store.all_steps()           # unpinned -> collected


# -- divergence -> rollback + LR backoff + skip window -----------------------

class TestRollback:
    def _healing_model(self, tmp_path, **policy_kw):
        from deeplearning4j_tpu.train.checkpoint import CheckpointStore
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        m = _model()
        store = CheckpointStore(str(tmp_path / "ck"), keep_last=3)
        m.add_listener(_saver(store, every=4))
        policy = RecoveryPolicy(
            store, quarantine_dir=str(tmp_path / "q"), **policy_kw
        ).attach(m)
        return m, store, policy

    def test_nan_step_rolls_back_with_lr_backoff_and_finishes_finite(
        self, tmp_path, monkeypatch
    ):
        from deeplearning4j_tpu.train.recovery import _LrScaledTx

        monkeypatch.setenv("DL4JTPU_CRASH_DIR", str(tmp_path))
        m, store, policy = self._healing_model(tmp_path, skip_window=2)
        rb_before = _counter("dl4jtpu_recovery_events_total", kind="rollback")
        faults.arm("data.decode:corrupt:nth=10")    # NaN step mid-fit
        m.fit(_feed(16), epochs=1)
        faults.disarm()
        assert policy.rollbacks == 1
        assert policy.lr_scale == 0.5
        assert isinstance(m._tx, _LrScaledTx)
        rollback = next(e for e in policy.events if e["kind"] == "rollback")
        assert rollback["restored_step"] <= rollback["from_iteration"]
        skipped = [e for e in policy.events if e["kind"] == "batch_skipped"]
        assert len(skipped) == 2
        assert np.isfinite(m.score_value)           # healed and trained on
        assert np.isfinite(
            np.asarray(list(m.param_table().values())[0])
        ).all()
        assert _counter(
            "dl4jtpu_recovery_events_total", kind="rollback"
        ) == rb_before + 1

    def test_rollback_budget_exhausts_into_divergence_error(
        self, tmp_path, monkeypatch
    ):
        from deeplearning4j_tpu.observe.health import DivergenceError

        monkeypatch.setenv("DL4JTPU_CRASH_DIR", str(tmp_path))
        m, store, policy = self._healing_model(
            tmp_path, max_rollbacks=1, skip_window=0
        )
        # two poisoned batches AFTER the first checkpoint (saved at
        # iteration 4): rollback #1 spends the budget, #2 is fatal
        faults.arm("data.decode:corrupt:nth=6;data.decode:corrupt:nth=8")
        with pytest.raises(DivergenceError):
            m.fit(_feed(16), epochs=1)
        faults.disarm()
        assert policy.rollbacks == 2                # budget 1 + the fatal one

    def test_rollback_skips_a_checkpoint_saved_with_nan_params(
        self, tmp_path, monkeypatch
    ):
        import jax

        monkeypatch.setenv("DL4JTPU_CRASH_DIR", str(tmp_path))
        m, store, policy = self._healing_model(tmp_path)
        m.fit(_feed(10), epochs=1)          # finite saves at steps 4, 8
        # a save cadence aligned with the divergence iteration can
        # checkpoint already-NaN params (the saver fires before the
        # HealthListener raises): fake one as the NEWEST entry — it is
        # intact, so CRC verification alone would hand it right back
        good = m.params
        m.params = jax.tree.map(
            lambda a: np.full_like(np.asarray(a), np.nan), m.params
        )
        store.save(m, step=12)
        m.params = good
        # the pin must NOT advance to the NaN save — otherwise keep_last
        # rotation could eat the finite steps the rollback will need
        assert policy._pinned == 8
        faults.arm("data.decode:corrupt:nth=2")
        m.fit(_feed(8, seed=1), epochs=1)
        faults.disarm()
        assert policy.rollbacks == 1
        rollback = next(e for e in policy.events if e["kind"] == "rollback")
        assert rollback["restored_step"] == 8       # NaN step-12 file skipped
        assert any(
            e["kind"] == "poisoned_checkpoint_skipped" and e["step"] == 12
            for e in policy.events
        )
        assert np.isfinite(m.score_value)
        assert np.isfinite(
            np.asarray(list(m.param_table().values())[0])
        ).all()

    def test_divergence_without_checkpoint_propagates(self, tmp_path,
                                                      monkeypatch):
        from deeplearning4j_tpu.observe.health import DivergenceError
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        monkeypatch.setenv("DL4JTPU_CRASH_DIR", str(tmp_path))
        m = _model()
        RecoveryPolicy(None).attach(m)              # no rollback source
        faults.arm("data.decode:corrupt:nth=3")
        with pytest.raises(DivergenceError):
            m.fit(_feed(6), epochs=1)
        faults.disarm()


# -- device OOM -> microbatch split ------------------------------------------

class TestOomMicrobatchSplit:
    def _oomify(self, m, threshold):
        real = m.fit_batch
        sizes = []

        def oomy(batch):
            sizes.append(batch.num_examples)
            if batch.num_examples > threshold:
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: Out of memory allocating "
                    "1234 bytes"
                )
            real(batch)

        m.fit_batch = oomy
        return sizes

    def test_split_doubles_until_it_fits_then_sticks(self):
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        m = _model()
        policy = RecoveryPolicy(None, max_split=8).attach(m)
        sizes = self._oomify(m, threshold=8)
        m.fit(_feed(4, batch=32), epochs=1)
        # first batch: 32 OOMs, 16 OOMs, 8 fits; later batches pre-split
        assert sizes[:3] == [32, 16, 8]
        assert policy.split_factor == 4
        assert m.iteration == 16                    # 4 batches x 4 pieces
        assert set(sizes[2:]) == {8}                # bounded program set
        assert [e["kind"] for e in policy.events] == ["oom_split"]
        assert np.isfinite(m.score_value)

    def test_partial_split_resumes_without_refitting(self):
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        m = _model()
        policy = RecoveryPolicy(None, max_split=8).attach(m)
        policy.split_factor = 2
        real = m.fit_batch
        calls = []

        def oomy(batch):
            calls.append(batch.num_examples)
            if batch.num_examples == 16 and calls.count(16) == 2:
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: Out of memory allocating"
                )
            real(batch)

        m.fit_batch = oomy
        m.fit(_feed(1, batch=32), epochs=1)
        # piece 0 (16 examples) stepped once; the OOMing remainder was
        # re-split to 8s WITHOUT refitting the already-stepped leading
        # examples (a refit would double-apply their updates)
        assert calls == [16, 16, 8, 8]
        assert m.iteration == 3
        assert policy.split_factor == 4

    def test_oom_past_the_split_cap_reraises(self):
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        m = _model()
        RecoveryPolicy(None, max_split=4).attach(m)
        self._oomify(m, threshold=1)                # nothing ever fits
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            m.fit(_feed(2, batch=16), epochs=1)

    def test_grouped_oom_disables_grouped_dispatch_for_the_fit(self):
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        m = _model()
        policy = RecoveryPolicy(None).attach(m)
        batches = list(_feed(4))
        runner_calls = []

        def oom_runner(bs):
            runner_calls.append(len(bs))
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory allocating 1234 bytes"
            )

        policy.run_group(m, batches[:2], oom_runner)
        assert runner_calls == [2]
        assert m.iteration == 2                 # retried individually
        # a deterministically-OOMing grouped program must not re-fire
        # on every flush: later groups route per-batch without ever
        # trying the runner again (split_factor may still be 1 — the
        # INDIVIDUAL batches fit fine)
        policy.run_group(m, batches[2:], oom_runner)
        assert runner_calls == [2]
        assert m.iteration == 4
        assert policy.split_factor == 1

    def test_non_oom_errors_pass_straight_through(self):
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        m = _model()
        RecoveryPolicy(None).attach(m)

        def broken(batch):
            raise ValueError("not an OOM")

        m.fit_batch = broken
        with pytest.raises(ValueError, match="not an OOM"):
            m.fit(_feed(2), epochs=1)


# -- poison batches -> quarantine --------------------------------------------

class TestPoisonBatchQuarantine:
    def test_corrupt_batch_is_screened_quarantined_and_fit_completes(
        self, tmp_path
    ):
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        m = _model()
        policy = RecoveryPolicy(
            None, quarantine_dir=str(tmp_path / "q"), scan_inputs=True
        ).attach(m)
        q_before = _counter(
            "dl4jtpu_quarantined_batches_total", reason="nonfinite_input"
        )
        faults.arm("data.decode:corrupt:nth=3")
        m.fit(_feed(8), epochs=1)
        faults.disarm()
        assert policy.quarantined == 1
        assert m.iteration == 7                     # poisoned batch dropped
        [rec] = policy.quarantine.entries()
        assert rec["reason"] == "nonfinite_input" and rec["has_bytes"]
        assert np.isnan(
            np.load(rec["path"].replace(".json", ".npz"))["features"]
        ).all()
        assert _counter(
            "dl4jtpu_quarantined_batches_total", reason="nonfinite_input"
        ) == q_before + 1
        assert np.isfinite(m.score_value)

    def test_decode_failure_is_quarantined_with_the_pulled_bytes(
        self, tmp_path
    ):
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        m = _model()
        policy = RecoveryPolicy(
            None, quarantine_dir=str(tmp_path / "q")
        ).attach(m)
        faults.arm("data.decode:raise:nth=2,exc=runtime")
        m.fit(_feed(6), epochs=1)
        faults.disarm()
        assert policy.quarantined == 1 and m.iteration == 5
        [rec] = policy.quarantine.entries()
        assert rec["reason"] == "decode_error" and "InjectedError" in rec["error"]
        # the pull succeeded before the decode boundary raised — the
        # record must carry the batch for offline replay
        assert rec["has_bytes"]
        npz = np.load(rec["path"].replace(".json", ".npz"))
        assert npz["features"].shape == (8, 4)

    def test_pull_failure_is_quarantined_without_bytes(self, tmp_path):
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        m = _model()
        policy = RecoveryPolicy(
            None, quarantine_dir=str(tmp_path / "q")
        ).attach(m)
        # the pull ITSELF raises: nothing was fetched, metadata only
        # (and the un-pulled batch isn't lost — all 6 still train)
        faults.arm("data.next_batch:raise:nth=2,exc=runtime")
        m.fit(_feed(6), epochs=1)
        faults.disarm()
        assert policy.quarantined == 1 and m.iteration == 6
        [rec] = policy.quarantine.entries()
        assert rec["reason"] == "decode_error" and not rec["has_bytes"]
        assert "InjectedError" in rec["error"]

    def test_quarantine_budget_exhaustion_fails_loudly(self, tmp_path):
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        m = _model()
        RecoveryPolicy(
            None, quarantine_dir=str(tmp_path / "q"), quarantine_cap=2
        ).attach(m)
        faults.arm("data.decode:raise:every=1,exc=runtime")
        with pytest.raises(faults.InjectedError):
            m.fit(_feed(8), epochs=1)
        faults.disarm()

    def test_restarted_run_inherits_spent_quarantine_budget(self, tmp_path):
        from deeplearning4j_tpu.data.quarantine import QuarantineStore
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        qdir = str(tmp_path / "q")
        prior = QuarantineStore(qdir, cap=2)
        prior.put("decode_error")
        prior.put("decode_error")
        # a fresh policy over the same directory starts with the budget
        # already spent — it must fail loudly, not silently drop batches
        policy = RecoveryPolicy(None, quarantine_dir=qdir, quarantine_cap=2)
        assert policy.quarantined == 2
        assert not policy.quarantine_pull_failure(object(), RuntimeError("x"))

    def test_programming_errors_in_the_feed_are_not_quarantined(
        self, tmp_path
    ):
        from deeplearning4j_tpu.data.iterator import DataSetIterator
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        m = _model()
        policy = RecoveryPolicy(
            None, quarantine_dir=str(tmp_path / "q")
        ).attach(m)

        class Broken(DataSetIterator):
            def reset(self):
                pass

            def __iter__(self):
                yield from _feed(2)
                raise TypeError("a bug in iterator code, not corrupt data")

        # a TypeError is a programming error to surface immediately,
        # not a poison record to skip up to the quarantine cap
        with pytest.raises(TypeError, match="a bug"):
            m.fit(Broken(), epochs=1)
        assert policy.quarantined == 0

    def test_without_policy_decode_failures_still_raise(self):
        m = _model()
        faults.arm("data.decode:raise:nth=2,exc=runtime")
        with pytest.raises(faults.InjectedError):
            m.fit(_feed(4), epochs=1)
        faults.disarm()


# -- supervisor hardening ----------------------------------------------------

class _FakeProc:
    def __init__(self, rc, delay=0.0):
        self._rc = rc
        self._delay = delay

    def wait(self, timeout=None):
        if self._delay:
            time.sleep(self._delay)
        return self._rc

    def poll(self):
        return self._rc


class _FakeServer:
    def __init__(self):
        self._lock = threading.Condition()
        self.expected = 0
        self.members = {}
        self.pending = {}
        self.evictions = []
        self.generation = 1
        self.heartbeat_timeout = 30.0


class TestSupervisorHardening:
    def test_crash_loop_gets_capped_exponential_backoff(self):
        from deeplearning4j_tpu.train.elastic import (
            EXIT_CONTROL_PLANE_LOST,
            ElasticSupervisor,
        )

        # control-plane-lost exits: no eviction-settle wall-clocking, so
        # the test isolates the backoff logic itself
        rcs = [[EXIT_CONTROL_PLANE_LOST]] * 4 + [[0]]

        def spawn(i, world, gen):
            return _FakeProc(rcs[gen - 1][i])

        sup = ElasticSupervisor(
            spawn, _FakeServer(), initial_world=1, min_world=1,
            max_generations=6, backoff_base=0.5, backoff_cap=2.0,
        )
        sleeps = []
        sup._sleep = sleeps.append
        sup.run(timeout=60)
        assert sleeps == [0.5, 1.0, 2.0, 2.0]       # doubled, then capped

    def test_backoff_is_visible_on_the_metrics_spine(self):
        """ISSUE 11 satellite: the crash-loop backoff state must show on
        /metrics (dl4jtpu_supervisor_backoff_seconds nonzero during the
        sleep, zero after) — respawn storms were log-only before."""
        from deeplearning4j_tpu.observe.metrics import registry
        from deeplearning4j_tpu.train.elastic import (
            EXIT_CONTROL_PLANE_LOST,
            ElasticSupervisor,
        )

        rcs = [[EXIT_CONTROL_PLANE_LOST], [0]]

        def spawn(i, world, gen):
            return _FakeProc(rcs[gen - 1][i])

        sup = ElasticSupervisor(
            spawn, _FakeServer(), initial_world=1, min_world=1,
            max_generations=3, backoff_base=0.7,
        )
        gauge = registry().gauge("dl4jtpu_supervisor_backoff_seconds")
        seen_during_sleep = []
        sup._sleep = lambda s: seen_during_sleep.append(gauge.value())
        sup.run(timeout=60)
        assert seen_during_sleep == [0.7]
        assert gauge.value() == 0.0          # reset once the sleep ends

    def test_slow_generation_resets_the_backoff_streak(self):
        from deeplearning4j_tpu.train.elastic import (
            EXIT_CONTROL_PLANE_LOST,
            ElasticSupervisor,
        )

        # fast crash, then a "long" generation (past the window), then ok
        procs = [[_FakeProc(EXIT_CONTROL_PLANE_LOST)],
                 [_FakeProc(EXIT_CONTROL_PLANE_LOST, delay=0.3)],
                 [_FakeProc(0)]]

        def spawn(i, world, gen):
            return procs[gen - 1][i]

        sup = ElasticSupervisor(
            spawn, _FakeServer(), initial_world=1, min_world=1,
            max_generations=4, crash_loop_window=0.2, backoff_base=0.5,
        )
        sleeps = []
        sup._sleep = sleeps.append
        sup.run(timeout=60)
        assert sleeps == [0.5]                      # only the fast crash

    def test_wedged_workers_respawn_without_shrinking(self):
        from deeplearning4j_tpu.train.elastic import ElasticSupervisor

        rcs = [[EXIT_STEP_WEDGED, EXIT_STEP_WEDGED], [0, 0]]
        worlds = []

        def spawn(i, world, gen):
            if i == 0:
                worlds.append(world)
            return _FakeProc(rcs[gen - 1][i])

        sup = ElasticSupervisor(
            spawn, _FakeServer(), initial_world=2, min_world=2,
            max_generations=3,
        )
        sup._sleep = lambda s: None
        t0 = time.perf_counter()
        sup.run(timeout=60)
        # no eviction-settle wall-clocking for pure watchdog aborts
        assert time.perf_counter() - t0 < 5.0
        assert worlds == [2, 2]
        assert sup.step_wedged_respawns == 2

    def test_dead_host_shrinks_even_when_an_eviction_is_late(self):
        from deeplearning4j_tpu.train.elastic import ElasticSupervisor

        # generation 1: worker0's watchdog aborted (respawn, no shrink),
        # worker1 hard-died — but only ONE (unattributed) eviction lands
        # before the settle wait expires.  The dead-worker count is
        # expect - wedged = 1 regardless of WHOSE eviction arrived, so
        # the world must still shrink by one.
        server = _FakeServer()
        server.heartbeat_timeout = 0.1          # short settle wait
        server.evictions.append(
            {"generation": 1, "worker": "w1", "reason": "heartbeat",
             "time": 0.0}
        )
        rcs = {1: [EXIT_STEP_WEDGED, 9], 2: [0]}
        worlds = []

        def spawn(i, world, gen):
            if i == 0:
                worlds.append(world)
            return _FakeProc(rcs[gen][i])

        sup = ElasticSupervisor(
            spawn, server, initial_world=2, min_world=1, max_generations=3,
        )
        sup._sleep = lambda s: None
        sup.run(timeout=60)
        assert worlds == [2, 1]
        assert sup.step_wedged_respawns == 1


# -- the chaos acceptance run ------------------------------------------------

class TestChaosEndToEnd:
    def test_hang_nan_and_poison_batch_in_one_fit(self, tmp_path,
                                                  monkeypatch):
        """ISSUE 6 acceptance: one seeded plan injects a device_sync
        hang, a decode failure and a NaN-poisoned batch into a single
        fit; training completes with a finite score and the watchdog /
        rollback / quarantine events all land on /metrics."""
        from deeplearning4j_tpu.observe.metrics import registry
        from deeplearning4j_tpu.train.checkpoint import CheckpointStore
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        monkeypatch.setenv("DL4JTPU_CRASH_DIR", str(tmp_path / "crash"))
        m = _model()
        store = CheckpointStore(str(tmp_path / "ck"), keep_last=3)
        m.add_listener(_saver(store, every=3))
        policy = RecoveryPolicy(
            store, skip_window=1, quarantine_dir=str(tmp_path / "q")
        ).attach(m)
        m._watchdog = StepWatchdog(floor_s=0.05, cold_floor_s=0.05, k=10.0)
        before = {
            "warn": _counter("dl4jtpu_watchdog_stalls_total", stage="warn"),
            "rollback": _counter("dl4jtpu_recovery_events_total",
                                 kind="rollback"),
            "quarantine": _counter("dl4jtpu_quarantined_batches_total",
                                   reason="decode_error"),
        }
        faults.arm(
            "device.sync:delay:nth=4,secs=0.4;"
            "data.decode:raise:nth=7,exc=runtime;"
            "data.decode:corrupt:nth=11"
        )
        m.fit(_feed(16), epochs=1)
        faults.disarm()
        # hang: watchdog fired and dumped stacks while the step wedged
        assert "warn" in [e["stage"] for e in m._watchdog.events]
        # NaN step: rolled back with LR backoff
        assert policy.rollbacks == 1 and policy.lr_scale == 0.5
        # poison batch: quarantined, not fatal
        assert policy.quarantined == 1
        # the run completed and is numerically healthy
        assert np.isfinite(m.score_value)
        # and every event is visible on the scrape path
        text = registry().to_prometheus_text()
        assert 'dl4jtpu_watchdog_stalls_total{stage="warn"}' in text
        assert 'dl4jtpu_recovery_events_total{kind="rollback"}' in text
        assert 'dl4jtpu_quarantined_batches_total{reason="decode_error"}' \
            in text
        assert _counter(
            "dl4jtpu_watchdog_stalls_total", stage="warn"
        ) >= before["warn"] + 1
        assert _counter(
            "dl4jtpu_recovery_events_total", kind="rollback"
        ) == before["rollback"] + 1
        assert _counter(
            "dl4jtpu_quarantined_batches_total", reason="decode_error"
        ) == before["quarantine"] + 1

    def test_grouped_fit_routes_through_recovery_chokepoint(
        self, tmp_path, monkeypatch
    ):
        """steps_per_execution fits recover too: a NaN batch inside a
        group still triggers rollback, and the grouped device-side step
        counter resyncs after the rewind."""
        from deeplearning4j_tpu.train.checkpoint import CheckpointStore
        from deeplearning4j_tpu.train.recovery import RecoveryPolicy

        monkeypatch.setenv("DL4JTPU_CRASH_DIR", str(tmp_path / "crash"))
        m = _model()
        store = CheckpointStore(str(tmp_path / "ck"), keep_last=3)
        m.add_listener(_saver(store, every=4))
        policy = RecoveryPolicy(store, skip_window=0).attach(m)
        faults.arm("data.decode:corrupt:nth=9")
        m.fit(_feed(16), epochs=1, steps_per_execution=2)
        faults.disarm()
        assert policy.rollbacks == 1
        assert np.isfinite(m.score_value)
