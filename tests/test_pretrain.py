"""AutoEncoder / VariationalAutoencoder layerwise pretraining.

Reference contract: MultiLayerNetwork.pretrain()/pretrainLayer() train
BasePretrainNetwork layers (AutoEncoder, VariationalAutoencoder)
unsupervised on features; the supervised forward then uses the encoder.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.models import SequentialModel
from deeplearning4j_tpu.nn import Adam
from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    AutoEncoder,
    Dense,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
    VariationalAutoencoder,
)
from deeplearning4j_tpu.nn.losses import Loss


def blobs(n=256, d=8, seed=0):
    rng = np.random.default_rng(seed)
    cls = rng.integers(0, 2, n)
    centers = np.stack([np.full(d, -1.0), np.full(d, 1.0)])
    x = centers[cls] + rng.normal(0, 0.4, (n, d))
    return x.astype(np.float32), np.eye(2, dtype=np.float32)[cls]


def _conf(pretrain_layer, d=8):
    return (
        NeuralNetConfiguration.builder()
        .seed(7)
        .updater(Adam(1e-2))
        .list()
        .layer(pretrain_layer)
        .layer(OutputLayer(n_out=2, loss=Loss.MCXENT, activation=Activation.SOFTMAX))
        .set_input_type(InputType.feed_forward(d))
        .build()
    )


def test_autoencoder_pretrain_reduces_loss():
    x, _ = blobs()
    ae = AutoEncoder(n_out=4, corruption_level=0.2, loss=Loss.MSE)
    model = SequentialModel(_conf(ae)).init()
    lp0 = model.params[model.conf.layers[0].name]
    import jax

    rng = jax.random.key(0)
    before = float(ae.pretrain_loss(jax.tree.map(lambda a: a, lp0), x, rng))
    model.pretrain_layer(0, (x, x[:, :2]), epochs=30, batch_size=128)
    after = float(
        ae.pretrain_loss(model.params[model.conf.layers[0].name], x, rng)
    )
    assert after < before * 0.7, (before, after)


def test_autoencoder_reconstruction_error_separates_anomalies():
    x, _ = blobs(n=128)
    ae = AutoEncoder(n_out=4, corruption_level=0.0, loss=Loss.MSE)
    model = SequentialModel(_conf(ae)).init()
    model.pretrain_layer(0, (x, x[:, :2]), epochs=40, batch_size=128)
    lp = model.params[model.conf.layers[0].name]
    err_in = np.asarray(ae.reconstruction_error(lp, x)).mean()
    anomalies = np.random.default_rng(3).normal(0, 4.0, (64, 8)).astype(np.float32)
    err_out = np.asarray(ae.reconstruction_error(lp, anomalies)).mean()
    assert err_out > err_in * 2, (err_in, err_out)


def test_pretrain_then_finetune_end_to_end():
    x, y = blobs()
    ae = AutoEncoder(n_out=4, corruption_level=0.1)
    model = SequentialModel(_conf(ae)).init()
    model.pretrain((x, y), epochs=10, batch_size=128)
    model.fit((x, y), epochs=20, batch_size=128)
    acc = model.evaluate(DataSet(x, y)).accuracy()
    assert acc > 0.9, acc


@pytest.mark.parametrize("dist", ["gaussian", "bernoulli"])
def test_vae_pretrain_elbo_improves(dist):
    x, _ = blobs(d=6)
    if dist == "bernoulli":
        x = (x > 0).astype(np.float32)   # binarize for bernoulli likelihood
    vae = VariationalAutoencoder(
        n_out=3, encoder_layer_sizes=(16,), decoder_layer_sizes=(16,),
        reconstruction_distribution=dist, num_samples=2,
    )
    model = SequentialModel(_conf(vae, d=6)).init()
    import jax

    rng = jax.random.key(1)
    name = model.conf.layers[0].name
    before = float(vae.pretrain_loss(model.params[name], x, rng))
    model.pretrain_layer(0, (x, x[:, :2]), epochs=30, batch_size=128)
    after = float(vae.pretrain_loss(model.params[name], x, rng))
    assert after < before, (before, after)


def test_vae_generate_and_log_prob_shapes():
    import jax

    x, _ = blobs(n=32, d=6)
    vae = VariationalAutoencoder(
        n_out=3, encoder_layer_sizes=(8,), decoder_layer_sizes=(8,),
    )
    model = SequentialModel(_conf(vae, d=6)).init()
    name = model.conf.layers[0].name
    lp = model.params[name]
    z = np.random.default_rng(0).normal(size=(5, 3)).astype(np.float32)
    out = vae.generate(lp, z)
    assert out.shape == (5, 6)
    logp = vae.reconstruction_log_probability(lp, x, jax.random.key(2), num_samples=3)
    assert logp.shape == (32,)
    assert np.all(np.isfinite(np.asarray(logp)))


def test_vae_supervised_forward_is_latent_mean():
    x, y = blobs(d=6)
    vae = VariationalAutoencoder(
        n_out=3, encoder_layer_sizes=(8,), decoder_layer_sizes=(8,),
    )
    model = SequentialModel(_conf(vae, d=6)).init()
    out = model.output(x[:4])
    assert out.shape == (4, 2)   # through the output layer
    model.fit((x, y), epochs=5, batch_size=128)   # supervised training works too
    assert np.isfinite(model.score_value)


def test_pretrain_serde_round_trip():
    from deeplearning4j_tpu.utils import serde

    ae = AutoEncoder(n_out=4, corruption_level=0.25, sparsity=0.05,
                     sparsity_beta=0.1, loss=Loss.RECONSTRUCTION_CROSSENTROPY)
    vae = VariationalAutoencoder(
        n_out=3, encoder_layer_sizes=(16, 8), decoder_layer_sizes=(8, 16),
        reconstruction_distribution="bernoulli", num_samples=4,
    )
    for layer in (ae, vae):
        back = serde.loads(serde.dumps(layer))
        assert back == layer, (layer, back)


def test_graph_model_pretrain_layer():
    """ComputationGraph.pretrainLayer parity: a VAE node inside a DAG is
    pretrained on its inference-mode ancestor activations."""
    import numpy as np
    from deeplearning4j_tpu.data.dataset import MultiDataSet
    from deeplearning4j_tpu.models.computation_graph import GraphModel
    from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder
    from deeplearning4j_tpu.nn.updaters import Adam as AdamUp

    x, y = blobs(d=6)
    g = (
        GraphBuilder().seed(4).updater(AdamUp(1e-2))
        .add_inputs("in")
        .set_input_types(InputType.feed_forward(6))
    )
    g.add_layer("ae", AutoEncoder(n_out=4, corruption_level=0.1), "in")
    g.add_layer("out", OutputLayer(n_out=2, loss=Loss.MCXENT,
                                   activation=Activation.SOFTMAX), "ae")
    g.set_outputs("out")
    model = GraphModel(g.build()).init()
    ae = model.conf.nodes[1].layer if model.conf.nodes[1].name == "ae" else None
    ae = ae or next(n.layer for n in model.conf.nodes if n.name == "ae")
    import jax

    before = float(ae.pretrain_loss(model.params["ae"], x, jax.random.key(0)))
    mds = MultiDataSet((x,), (y,))
    model.pretrain(mds, epochs=25)
    after = float(ae.pretrain_loss(model.params["ae"], x, jax.random.key(0)))
    assert after < before, (before, after)
    model.fit(mds, epochs=10)
    assert np.isfinite(model.score_value)


def test_graph_model_pretrain_rejects_non_pretrainable():
    import pytest as _pytest
    from deeplearning4j_tpu.models.computation_graph import GraphModel
    from deeplearning4j_tpu.nn.conf import Dense
    from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder

    g = (
        GraphBuilder().add_inputs("in")
        .set_input_types(InputType.feed_forward(4))
    )
    g.add_layer("d", Dense(n_out=3), "in")
    g.add_layer("out", OutputLayer(n_out=2), "d")
    g.set_outputs("out")
    model = GraphModel(g.build()).init()
    with _pytest.raises(ValueError, match="not pretrainable"):
        model.pretrain_layer("d", None)
    with _pytest.raises(KeyError):
        model.pretrain_layer("missing", None)
