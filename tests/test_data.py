"""Data pipeline tests: builtin datasets, normalizers."""

import numpy as np

from deeplearning4j_tpu.data import DataSet, NumpyDataSetIterator
from deeplearning4j_tpu.data.builtin import (
    CifarDataSetIterator,
    MnistDataSetIterator,
    synthetic_mnist,
)
from deeplearning4j_tpu.data.normalizers import (
    ImagePreProcessingScaler,
    Normalizer,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
    NormalizingIterator,
)


def test_synthetic_mnist_shapes_and_determinism():
    x1, y1 = synthetic_mnist(100, seed=3)
    x2, y2 = synthetic_mnist(100, seed=3)
    np.testing.assert_array_equal(x1, x2)
    np.testing.assert_array_equal(y1, y2)
    assert x1.shape == (100, 28, 28, 1)
    assert x1.min() >= 0.0 and x1.max() <= 1.0
    assert set(np.unique(y1)) <= set(range(10))


def test_mnist_iterator_batches():
    it = MnistDataSetIterator(batch_size=32, train=True, num_examples=100, seed=1)
    batches = list(it)
    assert len(batches) == 3  # 100 // 32
    b = batches[0]
    assert b.features.shape == (32, 28, 28, 1)
    assert b.labels.shape == (32, 10)
    np.testing.assert_allclose(b.labels.sum(axis=1), 1.0)


def test_mnist_classes_are_learnable_linear():
    """Sanity: a least-squares linear readout gets decent accuracy —
    the synthetic task carries real class signal."""
    x, y = synthetic_mnist(2000, seed=0)
    flat = x.reshape(len(x), -1)
    onehot = np.eye(10)[y]
    w, *_ = np.linalg.lstsq(flat, onehot, rcond=None)
    acc = (np.argmax(flat @ w, axis=1) == y).mean()
    assert acc > 0.8, f"linear acc {acc}"


def test_cifar_iterator():
    it = CifarDataSetIterator(batch_size=16, train=True, num_examples=64)
    b = next(iter(it))
    assert b.features.shape == (16, 32, 32, 3)


def test_normalizer_standardize_fit_transform_revert():
    rng = np.random.default_rng(0)
    x = rng.normal(5.0, 3.0, (200, 4)).astype(np.float32)
    y = np.zeros((200, 2), np.float32)
    it = NumpyDataSetIterator(x, y, batch_size=50, shuffle=False)
    norm = NormalizerStandardize().fit(it)
    out = norm.transform(DataSet(x, y))
    np.testing.assert_allclose(out.features.mean(axis=0), 0.0, atol=1e-4)
    np.testing.assert_allclose(out.features.std(axis=0), 1.0, atol=1e-3)
    back = norm.revert_features(out.features)
    np.testing.assert_allclose(back, x, rtol=1e-4)


def test_normalizer_save_restore(tmp_path):
    rng = np.random.default_rng(0)
    x = rng.normal(2.0, 1.5, (100, 3)).astype(np.float32)
    y = np.zeros((100, 1), np.float32)
    norm = NormalizerStandardize().fit(NumpyDataSetIterator(x, y, 25, shuffle=False))
    p = tmp_path / "norm.json"
    norm.save(str(p))
    restored = Normalizer.restore(str(p))
    np.testing.assert_allclose(restored.mean, norm.mean)
    np.testing.assert_allclose(restored.std, norm.std)


def test_minmax_and_image_scaler():
    x = np.arange(12, dtype=np.float32).reshape(4, 3)
    y = np.zeros((4, 1), np.float32)
    norm = NormalizerMinMaxScaler().fit(NumpyDataSetIterator(x, y, 2, shuffle=False))
    out = norm.transform(DataSet(x, y))
    assert out.features.min() == 0.0 and out.features.max() == 1.0
    img = ImagePreProcessingScaler().transform(
        DataSet(np.full((1, 2, 2, 1), 255.0, np.float32), y[:1])
    )
    assert img.features.max() == 1.0


def test_normalizing_iterator_wraps():
    x = np.random.default_rng(0).normal(10, 2, (64, 3)).astype(np.float32)
    y = np.zeros((64, 2), np.float32)
    base = NumpyDataSetIterator(x, y, 16, shuffle=False)
    norm = NormalizerStandardize().fit(base)
    wrapped = NormalizingIterator(base, norm)
    b = next(iter(wrapped))
    assert abs(b.features.mean()) < 0.5
