"""Performance attribution (observe/cost.py + trace additions): the
compiled-program registry, XLA cost analysis vs hand-computed FLOPs,
MFU/roofline gauges, build-info, trace ring drop accounting, and the
cross-worker trace merge."""

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.models import SequentialModel
from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    Dense,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.observe import cost, registry

pytestmark = pytest.mark.observe

B, I, O = 64, 256, 128


def dense_model(seed=1):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Sgd(0.01))
        .list()
        .layer(OutputLayer(n_out=O, loss=Loss.MSE,
                           activation=Activation.IDENTITY))
        .set_input_type(InputType.feed_forward(I))
        .build()
    )
    return SequentialModel(conf).init()


def batch(rng=None):
    rng = rng or np.random.default_rng(0)
    return DataSet(
        rng.normal(size=(B, I)).astype(np.float32),
        rng.normal(size=(B, O)).astype(np.float32),
    )


def train_records(model):
    return [r for r in cost.analyze_model(model) if r.kind == "train"]


class TestProgramRegistry:
    def test_flops_match_hand_computed_dense_matmul(self):
        """Acceptance: XLA cost-analysis FLOPs for a known dense-matmul
        model within 5% of hand-computed.  One Dense output layer's
        train step runs the forward matmul (2*B*I*O) and the dW matmul
        (2*B*I*O); the input-gradient matmul is dead code (no upstream
        layer wants it) and XLA DCEs it.  Bias/loss/updater terms are
        O(B*O + I*O) — under 2% at these dims."""
        m = dense_model()
        m.fit([batch()], epochs=1)
        recs = train_records(m)
        assert len(recs) == 1
        rec = recs[0]
        assert rec.analysis == "ok"
        hand = 4.0 * B * I * O
        assert abs(rec.flops - hand) / hand < 0.05
        assert rec.bytes_accessed > 0
        assert rec.signature is not None
        assert rec.dispatches == 1
        # first-dispatch compile tax was captured
        assert rec.backend_compiles >= 1
        assert rec.compile_secs > 0

    def test_memory_analysis_fields_guarded(self):
        m = dense_model()
        m.fit([batch()], epochs=1)
        rec = train_records(m)[0]
        rec.ensure_analysis(memory=True)
        d = rec.as_dict()
        # on CPU jax 0.4.37 these are present; the contract is "present
        # or None, never a raised analysis"
        if rec._memory_done and rec.argument_bytes is not None:
            assert d["argument_bytes"] > 0
            assert d["peak_bytes"] >= d["argument_bytes"]

    def test_no_cross_model_bleed_and_refit_reuses_entry(self):
        m1, m2 = dense_model(1), dense_model(2)
        m1.fit([batch()], epochs=1)
        m2.fit([batch()], epochs=1)
        mine = [r for r in cost.registry().programs()
                if r.owner_ref() in (m1, m2) and r.kind == "train"]
        owners = {id(r.owner_ref()) for r in mine}
        assert len(mine) == 2 and len(owners) == 2
        ids_before = {r.program_id for r in mine}
        # re-fit hits the cached step fn: same registry entries, more
        # dispatches, no new programs
        m1.fit([batch()], epochs=1)
        after = [r for r in cost.registry().programs()
                 if r.owner_ref() in (m1, m2) and r.kind == "train"]
        assert {r.program_id for r in after} == ids_before
        r1 = [r for r in after if r.owner_ref() is m1][0]
        assert r1.dispatches == 2

    def test_eviction_on_step_fn_cache_clear(self):
        """recovery's LR retrace (train/recovery.py) and re-distribute
        clear the model's step-fn cache; the registry must drop those
        programs instead of reporting stale entries."""
        m = dense_model()
        m.fit([batch()], epochs=1)
        assert train_records(m)
        m._step_fns.clear()     # what _LrScaledTx installation does
        assert [r for r in cost.registry().programs()
                if r.owner_ref() is m] == []
        # a fresh fit re-registers under a NEW record
        m.fit([batch()], epochs=1)
        recs = train_records(m)
        assert len(recs) == 1 and recs[0].dispatches == 1

    def test_dead_model_is_pruned(self):
        m = dense_model()
        m.fit([batch()], epochs=1)
        mid = id(m)
        del m
        import gc

        gc.collect()
        assert not any(
            id(r.owner_ref()) == mid
            for r in cost.registry().programs()
            if r.owner_ref() is not None
        )


class TestStepGauges:
    def test_mfu_and_flops_gauges_flow_after_analysis(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_PEAK_FLOPS", "1e12")
        monkeypatch.setenv("DL4J_TPU_PEAK_MEMBW", "1e11")
        m = dense_model()
        m.fit([batch()], epochs=1)
        rec = train_records(m)[0]     # triggers analysis
        reg = registry()
        flops_before = reg.counter(
            "dl4jtpu_step_model_flops_total"
        ).value()
        m.fit([batch()], epochs=3)
        flops_after = reg.counter("dl4jtpu_step_model_flops_total").value()
        assert flops_after - flops_before == pytest.approx(3 * rec.flops)
        ach = reg.gauge("dl4jtpu_step_achieved_flops_per_sec").value()
        mfu = reg.gauge("dl4jtpu_step_mfu").value()
        assert ach > 0
        import jax

        n = jax.local_device_count()
        assert mfu == pytest.approx(ach / (1e12 * n))
        assert reg.gauge("dl4jtpu_step_bytes_per_sec").value() > 0
        assert reg.gauge("dl4jtpu_step_membw_util").value() > 0

    def test_grouped_program_counts_k_steps_of_flops(self):
        """XLA cost analysis counts a lax.scan body ONCE, so the k-step
        grouped program reports ~single-step flops; the per-dispatch
        attribution must multiply by the group size."""
        rng = np.random.default_rng(3)
        m = dense_model()
        batches = [batch(rng) for _ in range(4)]
        m.fit(batches, epochs=1, steps_per_execution=4)
        recs = [r for r in cost.analyze_model(m)
                if r.kind == "train_multi"]
        assert len(recs) == 1
        rec = recs[0]
        # body-once: grouped flops within 10% of the single-step program
        hand = 4.0 * B * I * O
        assert abs(rec.flops - hand) / hand < 0.10
        reg = registry()
        before = reg.counter("dl4jtpu_step_model_flops_total").value()
        m.fit(batches, epochs=1, steps_per_execution=4)
        after = reg.counter("dl4jtpu_step_model_flops_total").value()
        assert after - before == pytest.approx(4 * rec.flops)

    def test_roofline_classification_follows_ridge(self, monkeypatch):
        m = dense_model()
        m.fit([batch()], epochs=1)
        rec = train_records(m)[0]
        ai = rec.arithmetic_intensity()
        assert ai > 0
        # ridge far below AI -> compute-bound; far above -> memory-bound
        monkeypatch.setenv("DL4J_TPU_PEAK_FLOPS", "1e12")
        monkeypatch.setenv("DL4J_TPU_PEAK_MEMBW", str(1e12 / (ai / 10)))
        assert rec.roofline() == "compute-bound"
        monkeypatch.setenv("DL4J_TPU_PEAK_MEMBW", str(1e12 / (ai * 10)))
        assert rec.roofline() == "memory-bound"

    def test_roofline_stamped_on_step_span(self, monkeypatch):
        from deeplearning4j_tpu.observe import tracer

        monkeypatch.setenv("DL4J_TPU_PEAK_FLOPS", "1e12")
        monkeypatch.setenv("DL4J_TPU_PEAK_MEMBW", "1e11")
        m = dense_model()
        m.fit([batch()], epochs=1)
        train_records(m)              # analyze
        t = tracer()
        t.enable()
        try:
            t.clear()
            m.fit([batch()], epochs=1)
            steps = [
                ev for ev in t.to_chrome_trace()["traceEvents"]
                if ev["name"] == "train_step"
            ]
            assert steps and steps[-1]["args"]["roofline"] in (
                "compute-bound", "memory-bound"
            )
        finally:
            t.disable()

    def test_program_table_shape(self):
        m = dense_model()
        m.fit([batch()], epochs=1)
        table = cost.program_table(analyze=True)
        mine = [row for row in table
                if row["kind"] == "train" and row["flops"]]
        assert mine
        row = mine[-1]
        for k in ("id", "model", "kind", "key", "signature", "dispatches",
                  "compile_secs", "flops", "bytes_accessed",
                  "arithmetic_intensity", "roofline", "analysis"):
            assert k in row


class TestBuildInfo:
    def test_build_info_series_is_self_describing(self):
        import jax

        from deeplearning4j_tpu.version import __version__

        text = registry().to_prometheus_text()
        lines = [l for l in text.splitlines()
                 if l.startswith("dl4jtpu_build_info{")]
        assert len(lines) == 1
        line = lines[0]
        assert f'version="{__version__}"' in line
        assert f'jax="{jax.__version__}"' in line
        assert 'backend="cpu"' in line
        assert 'device_count="' in line
        assert line.endswith(" 1")


class TestTraceDrops:
    def test_ring_wrap_counts_drops_and_stamps_metadata(self):
        from deeplearning4j_tpu.observe.trace import TraceRecorder

        t = TraceRecorder(capacity=8)
        t.enable()
        for i in range(20):
            t.add_complete(f"s{i}", float(i), 0.001)
        assert len(t) == 8
        assert t.spans_dropped == 12
        doc = t.to_chrome_trace()
        assert doc["metadata"]["spans_dropped"] == 12
        assert doc["metadata"]["capacity"] == 8

    def test_global_tracer_bridges_drops_to_counter(self):
        from deeplearning4j_tpu.observe import tracer

        t = tracer()
        was_enabled = t.enabled
        before = t.spans_dropped
        t.enable()
        try:
            for i in range(t.capacity + 5):
                t.add_complete("x", float(i), 0.0)
        finally:
            if not was_enabled:
                t.disable()
        assert t.spans_dropped >= before + 5
        reg = registry()
        reg.collect()
        assert reg.counter(
            "dl4jtpu_trace_spans_dropped_total"
        ).value() >= t.spans_dropped


class TestTraceMerge:
    def test_merged_cluster_trace_pid_mapping(self):
        from deeplearning4j_tpu.observe.trace import merge_chrome_traces

        def doc(name, dropped=0):
            return {
                "traceEvents": [
                    {"name": name, "ph": "X", "ts": 1.0, "dur": 2.0,
                     "pid": 4242, "tid": 1},
                ],
                "metadata": {"spans_dropped": dropped},
            }

        merged = merge_chrome_traces(
            {"w1": doc("a", dropped=3), "w0": doc("b")},
            pids={"w0": 0, "w1": 1},
        )
        evs = merged["traceEvents"]
        # per-worker process_name metadata events under the mapped pids
        names = {(e["pid"], e["args"]["name"]) for e in evs
                 if e.get("ph") == "M"}
        assert names == {(0, "w0"), (1, "w1")}
        spans = {(e["pid"], e["name"]) for e in evs if e.get("ph") == "X"}
        assert spans == {(0, "b"), (1, "a")}
        assert merged["metadata"]["spans_dropped"] == 3
        assert merged["metadata"]["workers"]["w1"]["pid"] == 1

    def test_merge_without_pids_uses_stable_sorted_index(self):
        from deeplearning4j_tpu.observe.trace import merge_chrome_traces

        merged = merge_chrome_traces({
            "b": {"traceEvents": []}, "a": {"traceEvents": []},
        })
        assert merged["metadata"]["workers"]["a"]["pid"] == 0
        assert merged["metadata"]["workers"]["b"]["pid"] == 1

    def test_merge_fallback_pids_stay_disjoint_from_explicit_ranks(self):
        """A rank-less worker's fallback pid must never collide with
        another worker's explicit rank — that would fuse two timelines
        under one Perfetto process."""
        from deeplearning4j_tpu.observe.trace import merge_chrome_traces

        merged = merge_chrome_traces(
            {"ranked": {"traceEvents": []},
             "anon1": {"traceEvents": []},
             "anon2": {"traceEvents": []}},
            pids={"ranked": 1},
        )
        w = merged["metadata"]["workers"]
        pids = {info["pid"] for info in w.values()}
        assert len(pids) == 3
        assert w["ranked"]["pid"] == 1
        assert w["anon1"]["pid"] == 0 and w["anon2"]["pid"] == 2

    def test_merge_duplicate_explicit_ranks_get_distinct_pids(self):
        """An elastic respawn can reuse a dead worker's rank while the
        dead worker's trace is still inside the fleet TTL — the two must
        not fuse under one pid."""
        from deeplearning4j_tpu.observe.trace import merge_chrome_traces

        merged = merge_chrome_traces(
            {"gen1-w": {"traceEvents": []},
             "gen2-w": {"traceEvents": []}},
            pids={"gen1-w": 0, "gen2-w": 0},
        )
        w = merged["metadata"]["workers"]
        assert w["gen1-w"]["pid"] != w["gen2-w"]["pid"]
        assert w["gen1-w"]["pid"] == 0          # first holder keeps it
