"""Device-compiled data pipeline (datavec/device.py): host-vs-device
transform parity, chain lowering + fallback semantics, and the fused
fit paths staging raw bytes."""

import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterator import DataSetIterator
from deeplearning4j_tpu.data.normalizers import (
    ImagePreProcessingScaler,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
    NormalizingIterator,
)
from deeplearning4j_tpu.datavec.device import (
    CenterCrop,
    Custom,
    DeviceDecode,
    DeviceTransformIterator,
    MeanPool,
    MinMaxScale,
    OneHot,
    PadToBucket,
    RandomCrop,
    RandomFlip,
    Scale,
    Standardize,
    TransformChain,
    chain_of,
    device_transform,
    raw_feed,
    try_lower,
)
from deeplearning4j_tpu.models import SequentialModel
from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    Dense,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.observe.metrics import registry

RNG = np.random.default_rng(7)
IMG_U8 = RNG.integers(0, 256, (8, 32, 32, 3)).astype(np.uint8)
IMG_F32 = RNG.normal(0, 1, (8, 32, 32, 3)).astype(np.float32)
IDS = RNG.integers(0, 5, 8)


def device_vs_host(chain, feats, labs, step=3):
    dec = DeviceDecode(chain)
    host = dec.host(step, DataSet(np.asarray(feats), np.asarray(labs)))
    df, dl, dfm, dlm = jax.jit(dec.fn)(jnp.uint32(step), feats, labs)
    return host, (np.asarray(df), np.asarray(dl),
                  None if dfm is None else np.asarray(dfm),
                  None if dlm is None else np.asarray(dlm))


def assert_parity(chain, feats, labs, step=3):
    host, (df, dl, dfm, dlm) = device_vs_host(chain, feats, labs, step)
    np.testing.assert_allclose(df, host.features, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(dl, host.labels, rtol=1e-6, atol=1e-6)
    if host.features_mask is None:
        assert dfm is None
    else:
        np.testing.assert_allclose(dfm, host.features_mask,
                                   rtol=1e-6, atol=1e-6)
    if host.labels_mask is None:
        assert dlm is None
    else:
        np.testing.assert_allclose(dlm, host.labels_mask,
                                   rtol=1e-6, atol=1e-6)


class TestParity:
    """Every lowered transform must produce numerically matching host
    and device outputs (1e-6 f32 tolerance; random transforms draw the
    same stream from the same fixed key)."""

    @pytest.mark.parametrize("feats", [IMG_U8, IMG_F32],
                             ids=["uint8", "f32"])
    def test_scale(self, feats):
        assert_parity(TransformChain((Scale(1 / 127.5, -1.0),),
                                     (OneHot(5),)), feats, IDS)

    @pytest.mark.parametrize("feats", [IMG_U8, IMG_F32],
                             ids=["uint8", "f32"])
    def test_standardize(self, feats):
        mean = np.float32([100.0, 120.0, 90.0])
        std = np.float32([40.0, 35.0, 50.0])
        assert_parity(TransformChain((Standardize(mean, std),), ()),
                      feats, IDS)

    @pytest.mark.parametrize("feats", [IMG_U8, IMG_F32],
                             ids=["uint8", "f32"])
    def test_minmax(self, feats):
        mn = np.zeros(3, np.float32)
        mx = np.full(3, 255.0, np.float32)
        assert_parity(TransformChain((MinMaxScale(mn, mx, -1, 1),), ()),
                      feats, IDS)

    @pytest.mark.parametrize("feats", [IMG_U8, IMG_F32],
                             ids=["uint8", "f32"])
    def test_crop_flip_fixed_key(self, feats):
        chain = TransformChain(
            (RandomCrop(24, 24), RandomFlip(0.5), CenterCrop(16, 16)),
            (OneHot(5),), seed=11,
        )
        assert_parity(chain, feats, IDS, step=5)

    def test_random_transforms_vary_by_step_not_by_path(self):
        chain = TransformChain((RandomCrop(24, 24), RandomFlip(0.5)),
                               (), seed=11)
        dec = DeviceDecode(chain)
        a = np.asarray(jax.jit(dec.fn)(jnp.uint32(1), IMG_U8, IDS)[0])
        b = np.asarray(jax.jit(dec.fn)(jnp.uint32(2), IMG_U8, IDS)[0])
        assert not np.array_equal(a, b)   # per-step augmentation stream
        h = dec.host(1, DataSet(IMG_U8, IDS)).features
        np.testing.assert_array_equal(a, h)   # same step = same draw

    def test_mean_pool_resize(self):
        chain = TransformChain(
            (Scale(1 / 127.5, -1.0),
             MeanPool((8, 8), collapse_channels=True)),
            (OneHot(5),),
        )
        assert_parity(chain, IMG_U8, IDS)
        host, (df, _, _, _) = device_vs_host(chain, IMG_U8, IDS)
        assert df.shape == (8, 4, 4, 1)

    def test_one_hot(self):
        host, (_, dl, _, _) = device_vs_host(
            TransformChain((), (OneHot(5),)), IMG_U8, IDS
        )
        assert dl.shape == (8, 5)
        np.testing.assert_array_equal(dl, np.eye(5, dtype=np.float32)[IDS])

    def test_sequence_pad_and_mask(self):
        seq = RNG.normal(0, 1, (4, 37, 6)).astype(np.float32)
        seq_labels = RNG.normal(0, 1, (4, 37, 2)).astype(np.float32)
        chain = TransformChain((PadToBucket(16),), (PadToBucket(16),))
        host, (df, dl, dfm, dlm) = device_vs_host(chain, seq, seq_labels)
        assert df.shape == (4, 48, 6) and dl.shape == (4, 48, 2)
        assert dfm.shape == (4, 48) and dlm.shape == (4, 48)
        np.testing.assert_array_equal(dfm[:, :37], 1.0)
        np.testing.assert_array_equal(dfm[:, 37:], 0.0)
        np.testing.assert_array_equal(df, host.features)
        np.testing.assert_array_equal(dfm, host.features_mask)

    def test_pad_aligned_length_is_identity(self):
        seq = RNG.normal(0, 1, (2, 32, 3)).astype(np.float32)
        chain = TransformChain((PadToBucket(16),), ())
        _, (df, _, dfm, _) = device_vs_host(chain, seq, IDS[:2])
        assert df.shape == (2, 32, 3)
        np.testing.assert_array_equal(dfm, 1.0)

    def test_marked_custom_transform_lowers_and_matches(self):
        @device_transform
        def double(x, key):
            return x.astype(jnp.float32) * 2.0

        chain = TransformChain((Custom(double),), ())
        dec, reason = try_lower(chain)
        assert dec is not None, reason
        assert_parity(chain, IMG_F32, IDS)


class TestLowering:
    def test_unmarked_custom_refuses_with_reason(self):
        def opaque(x, key):
            return x

        dec, reason = try_lower(TransformChain((Custom(opaque),), ()))
        assert dec is None
        assert "not marked @device_transform" in reason

    def test_unknown_spec_type_refuses(self):
        dec, reason = try_lower(TransformChain(("not a transform",), ()))
        assert dec is None
        assert "unknown transform" in reason

    def test_fingerprint_distinguishes_custom_closures(self):
        # two closures from the same factory share a qualname but
        # capture different values — their fingerprints must differ, or
        # the fused step-fn cache would replay the first one's program
        def make(c):
            @device_transform
            def adjust(x, key):
                return x * c

            return adjust

        a = TransformChain((Custom(make(0.5)),), ())
        b = TransformChain((Custom(make(0.9)),), ())
        assert a.fingerprint() != b.fingerprint()
        f = make(0.5)
        assert (TransformChain((Custom(f),), ()).fingerprint()
                == TransformChain((Custom(f),), ()).fingerprint())

    def test_fingerprint_distinguishes_stats(self):
        a = TransformChain((Standardize(np.float32([1.0]),
                                        np.float32([2.0])),), ())
        b = TransformChain((Standardize(np.float32([1.5]),
                                        np.float32([2.0])),), ())
        assert a.fingerprint() != b.fingerprint()

    def test_normalizers_advertise_their_lowering(self):
        std = NormalizerStandardize()
        assert std.device_spec() is None          # not fitted
        std.mean = np.float32([1.0])
        std.std = np.float32([2.0])
        assert isinstance(std.device_spec(), Standardize)
        mm = NormalizerMinMaxScaler()
        mm.min, mm.max = np.float32([0.0]), np.float32([1.0])
        assert isinstance(mm.device_spec(), MinMaxScale)
        assert isinstance(ImagePreProcessingScaler().device_spec(), Scale)


class _RawImageFeed(DataSetIterator):
    """Undecoded camera-wire batches: uint8 images + int class ids."""

    def __init__(self, n_batches=6, batch=16, hw=(16, 16, 3), n_cls=3):
        rng = np.random.default_rng(3)
        self._n, self._b = n_batches, batch
        self._x = rng.integers(
            0, 256, (n_batches * batch,) + hw
        ).astype(np.uint8)
        self._y = rng.integers(0, n_cls, n_batches * batch)

    @property
    def batch_size(self):
        return self._b

    def reset(self):
        pass

    def __iter__(self):
        for i in range(self._n):
            sl = slice(i * self._b, (i + 1) * self._b)
            yield DataSet(self._x[sl], self._y[sl])


def _mlp(n_in, n_cls=3, seed=5):
    conf = (
        NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.05))
        .list()
        .layer(Dense(n_out=16, activation=Activation.RELU))
        .layer(OutputLayer(n_out=n_cls, loss=Loss.MCXENT,
                           activation=Activation.SOFTMAX))
        .set_input_type(InputType.convolutional(*n_in))
        .build()
    )
    return SequentialModel(conf).init()


CHAIN = TransformChain(
    (Scale(1 / 127.5, -1.0), MeanPool((4, 4), collapse_channels=True)),
    (OneHot(3),),
)


class TestFusedFit:
    def test_iterator_protocol(self):
        it = DeviceTransformIterator(_RawImageFeed(), CHAIN)
        assert chain_of(it) is CHAIN
        raw = raw_feed(it)
        batches = list(raw)
        assert len(batches) == 6
        assert all(b._raw_for_device_decode for b in batches)
        assert batches[0].features.dtype == np.uint8
        # the host path decodes
        host = next(iter(it))
        assert host.features.shape == (16, 4, 4, 1)
        assert host.labels.shape == (16, 3)

    def test_fused_fit_stages_raw_and_counts(self):
        reg = registry()
        dec_batches = reg.counter("dl4jtpu_device_decode_batches_total")
        dec_secs = reg.counter("dl4jtpu_device_decode_seconds_total")
        h2d_raw = reg.counter("dl4jtpu_h2d_bytes_total")
        b0, s0 = dec_batches.value(), dec_secs.value()
        r0 = h2d_raw.value(feed="raw")
        m = _mlp((4, 4, 1))
        m.fit(DeviceTransformIterator(_RawImageFeed(), CHAIN), epochs=2)
        assert m.iteration == 12
        assert np.isfinite(m.score_value)
        assert dec_batches.value() - b0 == 12
        assert dec_secs.value() > s0
        # 12 raw uint8 batches crossed H2D: 16 * 16*16*3 u8 + 16 * 8B ids
        assert h2d_raw.value(feed="raw") - r0 >= 12 * 16 * 16 * 16 * 3

    def test_fused_matches_host_path_loss(self):
        # identical feed, transforms on device vs on host: same shapes,
        # comparable converged loss (no augmentation in this chain, so
        # the two runs see byte-identical decoded batches)
        from deeplearning4j_tpu.runtime.flags import environment

        m_dev = _mlp((4, 4, 1))
        m_dev.fit(DeviceTransformIterator(_RawImageFeed(), CHAIN),
                  epochs=2)
        env = environment()
        env.device_decode = False
        try:
            m_host = _mlp((4, 4, 1))
            m_host.fit(DeviceTransformIterator(_RawImageFeed(), CHAIN),
                       epochs=2)
        finally:
            env.device_decode = True
        np.testing.assert_allclose(
            float(m_dev.score_value), float(m_host.score_value),
            rtol=1e-4, atol=1e-5,
        )

    def test_grouped_fused_fit(self):
        reg = registry()
        dec_batches = reg.counter("dl4jtpu_device_decode_batches_total")
        b0 = dec_batches.value()
        m = _mlp((4, 4, 1))
        m.fit(DeviceTransformIterator(_RawImageFeed(), CHAIN),
              epochs=1, steps_per_execution=3)
        assert m.iteration == 6
        assert dec_batches.value() - b0 == 6
        # the grouped fused program is ONE compiled step program
        assert m.compile_stats()["step_programs"] <= 2

    def test_unlowerable_chain_falls_back_and_logs(self, caplog):
        def opaque(x, key):
            return np.asarray(x, np.float32) / 255.0

        chain = TransformChain((Custom(opaque), MeanPool((4, 4),
                                                         True)),
                               (OneHot(3),))
        reg = registry()
        fallbacks = reg.counter("dl4jtpu_device_decode_fallbacks_total")
        dec_batches = reg.counter("dl4jtpu_device_decode_batches_total")
        b0 = dec_batches.value()
        m = _mlp((4, 4, 1))
        with caplog.at_level(logging.INFO, logger="deeplearning4j_tpu"):
            m.fit(DeviceTransformIterator(_RawImageFeed(), chain),
                  epochs=1)
        assert m.iteration == 6                  # host path still trains
        assert dec_batches.value() == b0         # nothing fused
        _, reason = try_lower(chain)
        assert "not marked @device_transform" in reason
        assert fallbacks.value(reason=reason) >= 1
        assert any("device decode fallback" in r.message
                   for r in caplog.records)

    def test_flag_off_keeps_host_path(self):
        from deeplearning4j_tpu.runtime.flags import environment

        reg = registry()
        dec_batches = reg.counter("dl4jtpu_device_decode_batches_total")
        b0 = dec_batches.value()
        env = environment()
        env.device_decode = False
        try:
            m = _mlp((4, 4, 1))
            m.fit(DeviceTransformIterator(_RawImageFeed(), CHAIN),
                  epochs=1)
        finally:
            env.device_decode = True
        assert m.iteration == 6
        assert dec_batches.value() == b0

    def test_normalizing_iterator_fuses(self):
        rng = np.random.default_rng(1)
        x = rng.normal(0, 1, (64, 8)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]

        class Feed(DataSetIterator):
            @property
            def batch_size(self):
                return 16

            def reset(self):
                pass

            def __iter__(self):
                for b in DataSet(x, y).split_batches(16):
                    yield b

        norm = NormalizerStandardize().fit(Feed())
        reg = registry()
        dec_batches = reg.counter("dl4jtpu_device_decode_batches_total")
        b0 = dec_batches.value()
        conf = (
            NeuralNetConfiguration.builder().seed(5).updater(Sgd(0.05))
            .list()
            .layer(Dense(n_out=8, activation=Activation.RELU))
            .layer(OutputLayer(n_out=3, loss=Loss.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build()
        )
        m = SequentialModel(conf).init()
        m.fit(NormalizingIterator(Feed(), norm), epochs=1)
        assert m.iteration == 4
        assert dec_batches.value() - b0 == 4


class TestMaskedAndFrozenBatches:
    def test_host_application_threads_batch_masks_through(self):
        # the host path must hand the fit loop what the pre-chain
        # iterator stack would have: batch masks preserved (and
        # extended by mask-producing specs), not dropped
        seq = RNG.normal(0, 1, (4, 37, 6)).astype(np.float32)
        labs = RNG.normal(0, 1, (4, 37, 2)).astype(np.float32)
        fmask = np.ones((4, 37), np.float32)
        fmask[:, 30:] = 0.0
        dec = DeviceDecode(TransformChain((Scale(2.0),), ()))
        out = dec.host(0, DataSet(seq, labs, fmask, None))
        np.testing.assert_array_equal(out.features_mask, fmask)
        # a padding spec EXTENDS the incoming mask
        dec2 = DeviceDecode(TransformChain((PadToBucket(16),), ()))
        out2 = dec2.host(0, DataSet(seq, labs, fmask, None))
        assert out2.features_mask.shape == (4, 48)
        np.testing.assert_array_equal(out2.features_mask[:, :37], fmask)
        np.testing.assert_array_equal(out2.features_mask[:, 37:], 0.0)

    def test_masked_raw_batch_declines_fusion_and_keeps_masks(self):
        # raw batches carrying their own masks cannot fuse (the fused
        # program stages features/labels only): the raw feed
        # host-decodes them while still numpy — a tagged masked batch
        # would be prefetch-staged to the device raw and pay a hidden
        # D2H for its per-step decode, with its bytes misattributed to
        # the raw-feed H2D series
        class MaskedRawFeed(_RawImageFeed):
            def __iter__(self):
                for b in super().__iter__():
                    yield DataSet(b.features, b.labels,
                                  None,
                                  np.ones(b.num_examples, np.float32))

        reg = registry()
        dec_batches = reg.counter("dl4jtpu_device_decode_batches_total")
        h2d = reg.counter("dl4jtpu_h2d_bytes_total")
        b0 = dec_batches.value()
        r0 = h2d.value(feed="raw")
        m = _mlp((4, 4, 1))
        m.fit(DeviceTransformIterator(MaskedRawFeed(), CHAIN), epochs=1)
        assert m.iteration == 6
        assert np.isfinite(m.score_value)
        assert dec_batches.value() == b0          # nothing fused
        assert h2d.value(feed="raw") == r0        # no bytes fed raw

    def test_augment_keys_follow_feed_counter_not_iteration(self):
        # the fused program folds augmentation keys from the feed's
        # counter (batch._decode_step), NOT model.iteration: an
        # evaluate() between fits advances only the feed counter, so
        # keying off iteration would desync the fused path from the
        # host fallback and break the flag's numerics-neutrality
        aug_chain = TransformChain(
            (Scale(1 / 127.5, -1.0), RandomFlip(0.5),
             MeanPool((4, 4), collapse_channels=True)),
            (OneHot(3),), seed=9,
        )
        from deeplearning4j_tpu.runtime.flags import environment

        def run(device_decode):
            env = environment()
            env.device_decode = device_decode
            try:
                it = DeviceTransformIterator(_RawImageFeed(), aug_chain)
                m = _mlp((4, 4, 1))
                m.fit(it, epochs=1)      # feed counter 0..5
                m.evaluate(it)           # host pass: counter 6..11
                m.fit(it, epochs=1)      # second fit draws keys 12..17
                return float(m.score_value)
            finally:
                env.device_decode = True

        np.testing.assert_allclose(run(True), run(False),
                                   rtol=1e-4, atol=1e-5)

    def test_mixed_tag_group_degrades_to_per_batch(self):
        # a grouped (steps_per_execution) buffer mixing raw-tagged
        # DataSets with host-decoded foreign batches must NOT dispatch
        # the grouped program — it would stack the tagged batches'
        # undecoded bytes into the loss.  The group degrades to
        # per-batch steps, where every raw batch is decoded (fused).
        class SlottedDS:
            __slots__ = ("features", "labels", "features_mask",
                         "labels_mask")

            def __init__(self, f, l):
                self.features, self.labels = f, l
                self.features_mask = self.labels_mask = None

            @property
            def num_examples(self):
                return int(self.features.shape[0])

        # shape/dtype-preserving chain + pre-one-hot labels: raw f32
        # and host-decoded f32 batches look identical to the group's
        # shape checks, only the raw tag tells them apart
        chain = TransformChain((Scale(2.0, 0.0),), ())

        class MixedFeed(DataSetIterator):
            @property
            def batch_size(self):
                return 8

            def reset(self):
                pass

            def __iter__(self):
                rng = np.random.default_rng(11)
                for i in range(4):
                    f = rng.normal(0, 1, (8, 4, 4, 1)).astype(np.float32)
                    l = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
                    yield (SlottedDS(f, l) if i % 2 else DataSet(f, l))

        reg = registry()
        dec_batches = reg.counter("dl4jtpu_device_decode_batches_total")
        b0 = dec_batches.value()
        m = _mlp((4, 4, 1))
        m.fit(DeviceTransformIterator(MixedFeed(), chain), epochs=1,
              steps_per_execution=4)
        assert m.iteration == 4
        assert np.isfinite(m.score_value)
        # the 2 raw-tagged batches were decoded per-batch (fused),
        # never handed undecoded to the grouped program
        assert dec_batches.value() - b0 == 2

    def test_normalizing_iterator_chain_is_stable(self):
        # device_chain must hand back the SAME chain object across
        # accesses: try_lower memoizes the lowering (and its decode
        # calibration) ON the chain, so a fresh chain per access would
        # re-pay the calibration on every fit.  Re-parameterizing the
        # normalizer changes the spec fingerprint and invalidates.
        norm = ImagePreProcessingScaler(0.0, 1.0)
        it = NormalizingIterator(_RawImageFeed(), norm)
        c1 = it.device_chain
        assert it.device_chain is c1
        d1, _ = try_lower(c1)
        d2, _ = try_lower(it.device_chain)
        assert d1 is d2
        norm.lo = 0.5
        assert it.device_chain is not c1

    def test_untaggable_raw_batch_is_host_decoded_not_fed_raw(self):
        # a slotted batch type cannot carry the routing tag — the raw
        # feed must host-decode it, never hand undecoded bytes to the
        # non-fused step
        class SlottedDS:
            __slots__ = ("features", "labels", "features_mask",
                         "labels_mask")

            def __init__(self, f, l):
                self.features, self.labels = f, l
                self.features_mask = self.labels_mask = None

            @property
            def num_examples(self):
                return int(self.features.shape[0])

        class FrozenRawFeed(_RawImageFeed):
            def __iter__(self):
                for b in super().__iter__():
                    yield SlottedDS(b.features, b.labels)

        reg = registry()
        dec_batches = reg.counter("dl4jtpu_device_decode_batches_total")
        b0 = dec_batches.value()
        m = _mlp((4, 4, 1))
        m.fit(DeviceTransformIterator(FrozenRawFeed(), CHAIN), epochs=1)
        assert m.iteration == 6
        assert np.isfinite(m.score_value)
        assert dec_batches.value() == b0          # nothing fused


@pytest.mark.faults
class TestFaultSite:
    def test_device_decode_fault_site_fires(self):
        from deeplearning4j_tpu.runtime import faults

        faults.arm("data.device_decode:raise:nth=2")
        try:
            m = _mlp((4, 4, 1))
            with pytest.raises(faults.InjectedFault):
                m.fit(DeviceTransformIterator(_RawImageFeed(), CHAIN),
                      epochs=1)
            assert m.iteration == 1      # step 1 trained, step 2 raised
        finally:
            faults.disarm()
