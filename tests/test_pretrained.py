"""Pretrained registry tests — init_pretrained + checksummed local
registry (ZooModel.initPretrained/PretrainedType roles)."""

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.zoo.lenet import LeNet
from deeplearning4j_tpu.zoo.pretrained import (
    ChecksumMismatchError,
    ENV_PRETRAINED_DIR,
    PretrainedRegistry,
)


@pytest.fixture
def registry(tmp_path, monkeypatch):
    monkeypatch.setenv(ENV_PRETRAINED_DIR, str(tmp_path / "models"))
    return PretrainedRegistry()


def trained_lenet_zip(tmp_path):
    m = LeNet(num_classes=3, height=12, width=12).init_model()
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (16, 12, 12, 1)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    m.fit_batch(DataSet(x, y))
    p = str(tmp_path / "weights.zip")
    m.save(p)
    return m, p, x


class TestRegistry:
    def test_register_resolve_init_pretrained_roundtrip(self, registry, tmp_path):
        m, p, x = trained_lenet_zip(tmp_path)
        entry = registry.register("lenet", "mnist", p)
        assert len(entry["sha256"]) == 64
        loaded = LeNet(num_classes=3, height=12, width=12).init_pretrained("mnist")
        np.testing.assert_allclose(
            np.asarray(m.output(x)), np.asarray(loaded.output(x)),
            rtol=1e-5, atol=1e-6,
        )
        assert registry.available("lenet") == {"mnist": entry}

    def test_corruption_detected(self, registry, tmp_path):
        _, p, _ = trained_lenet_zip(tmp_path)
        registry.register("lenet", "mnist", p)
        # corrupt the registered copy
        target = registry.root / "lenet_mnist.zip"
        data = bytearray(target.read_bytes())
        data[100] ^= 0xFF
        target.write_bytes(bytes(data))
        with pytest.raises(ChecksumMismatchError, match="sha256"):
            registry.resolve("lenet", "mnist")

    def test_missing_registration_names_alternatives(self, registry, tmp_path):
        _, p, _ = trained_lenet_zip(tmp_path)
        registry.register("lenet", "mnist", p)
        with pytest.raises(FileNotFoundError, match="mnist"):
            registry.resolve("lenet", "imagenet")

    def test_legacy_bare_zip_layout_still_loads(self, registry, tmp_path):
        m, p, x = trained_lenet_zip(tmp_path)
        registry.root.mkdir(parents=True, exist_ok=True)
        import shutil

        shutil.copyfile(p, registry.root / "lenet.zip")   # pre-registry layout
        loaded = LeNet(num_classes=3, height=12, width=12).init_pretrained()
        np.testing.assert_allclose(
            np.asarray(m.output(x)), np.asarray(loaded.output(x)),
            rtol=1e-5, atol=1e-6,
        )

    def test_explicit_path_bypasses_registry(self, registry, tmp_path):
        m, p, x = trained_lenet_zip(tmp_path)
        loaded = LeNet(num_classes=3, height=12, width=12).init_pretrained(path=p)
        np.testing.assert_allclose(
            np.asarray(m.output(x)), np.asarray(loaded.output(x)),
            rtol=1e-5, atol=1e-6,
        )
