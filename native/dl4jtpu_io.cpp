// dl4jtpu_io — native data-loading runtime for the host side of the TPU
// framework.
//
// Role: the reference keeps its ETL hot paths native (libnd4j buffer
// routines + JavaCV/OpenCV decoders behind DataVec — SURVEY.md §2.2
// "DataVec"); the TPU build's equivalent is this small C++ library behind
// ctypes (runtime/native.py): multithreaded CSV -> float32 matrices, IDX
// (MNIST-family) decoding, and uint8 -> float32 scale/shift batch
// conversion.  The device math all lives in XLA; this tier exists so the
// input pipeline can feed it at memory bandwidth instead of Python-object
// speed.
//
// Build: `make` in this directory (g++ -O3 -shared -fPIC -pthread).
// Pure C ABI — no Python.h, no external deps.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <string>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// memory
// ---------------------------------------------------------------------------

void dl4jtpu_free(void* p) { std::free(p); }

// ---------------------------------------------------------------------------
// CSV -> float32 row-major matrix
// ---------------------------------------------------------------------------

namespace {

static const double kPow10[] = {
    1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,  1e8,  1e9,
    1e10, 1e11, 1e12, 1e13, 1e14, 1e15, 1e16, 1e17, 1e18};

// hand-rolled float parser ([-]ddd[.ddd][e[+-]dd]): integer-accumulation
// based, ~5-10x strtof (no locale machinery).  Falls back to strtof for
// pathological exponents/overlong mantissas.
static inline float parse_f32(const char* p, const char* end,
                              const char** out_next) {
  const char* start = p;
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) {
    neg = (*p == '-');
    p++;
  }
  uint64_t mant = 0;
  int digits = 0, frac = 0;
  while (p < end && *p >= '0' && *p <= '9') {
    mant = mant * 10 + (*p - '0');
    digits++;
    p++;
  }
  if (p < end && *p == '.') {
    p++;
    while (p < end && *p >= '0' && *p <= '9') {
      mant = mant * 10 + (*p - '0');
      digits++;
      frac++;
      p++;
    }
  }
  int exp10 = 0;
  if (p < end && (*p == 'e' || *p == 'E')) {
    p++;
    bool eneg = false;
    if (p < end && (*p == '-' || *p == '+')) {
      eneg = (*p == '-');
      p++;
    }
    while (p < end && *p >= '0' && *p <= '9') {
      exp10 = exp10 * 10 + (*p - '0');
      p++;
    }
    if (eneg) exp10 = -exp10;
  }
  if (digits == 0 || digits > 18) {      // nan/inf/overlong: defer to libc
    char* next = nullptr;
    float v = std::strtof(start, &next);
    *out_next = (next == start) ? start : next;
    if (next == start) v = 0.0f;
    return v;
  }
  int e = exp10 - frac;
  double v = static_cast<double>(mant);
  if (e > 0) {
    v = (e <= 18) ? v * kPow10[e] : v * std::pow(10.0, e);
  } else if (e < 0) {
    v = (-e <= 18) ? v / kPow10[-e] : v / std::pow(10.0, -e);
  }
  *out_next = p;
  return static_cast<float>(neg ? -v : v);
}

// parse one line of exactly `cols` floats; returns cols on success,
// -1 on a malformed field (empty/non-numeric — numpy raises there too),
// cols+1 when the row has extra fields (ragged), or the short count.
static long parse_line(const char* p, const char* end, char delim,
                       float* out, long cols) {
  long c = 0;
  while (p < end && c < cols) {
    while (p < end && (*p == ' ' || *p == '\t') && *p != delim) p++;
    const char* next = p;
    out[c++] = parse_f32(p, end, &next);
    if (next == p) return -1;          // field did not parse as a number
    p = next;
    while (p < end && (*p == ' ' || *p == '\t') && *p != delim) p++;
    if (p < end && *p != delim && *p != '\n' && *p != '\r') {
      return -1;                       // trailing junk inside the field
    }
    while (p < end && *p != delim && *p != '\n') p++;
    if (p < end && *p == delim) p++;
    else break;                        // end of line
  }
  if (c == cols && p < end && *p != '\n') {
    // more data after the last expected field -> ragged (extra columns)
    return cols + 1;
  }
  return c;
}

struct Slice {
  const char* begin;
  const char* end;
  long row0;
};

}  // namespace

// Parse a CSV file of numbers into a newly-malloc'd float32 row-major
// matrix.  Lines are split across n_threads workers.  Returns 0 on
// success; negative error codes otherwise.
int dl4jtpu_csv_read_f32(const char* path, char delim, long skip_rows,
                         float** out_data, long* out_rows, long* out_cols,
                         int n_threads) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  char* buf = static_cast<char*>(std::malloc(size + 1));
  if (!buf) {
    std::fclose(f);
    return -2;
  }
  if (std::fread(buf, 1, size, f) != static_cast<size_t>(size)) {
    std::free(buf);
    std::fclose(f);
    return -3;
  }
  std::fclose(f);
  buf[size] = '\n';

  // index line starts
  std::vector<const char*> lines;
  lines.reserve(size / 16);
  const char* end = buf + size;
  const char* p = buf;
  while (p < end) {
    const char* nl = static_cast<const char*>(std::memchr(p, '\n', end - p));
    if (!nl) nl = end;
    if (nl > p) lines.push_back(p);          // skip empty lines
    p = nl + 1;
  }
  if (static_cast<long>(lines.size()) <= skip_rows) {
    std::free(buf);
    return -4;
  }
  lines.erase(lines.begin(), lines.begin() + skip_rows);
  long rows = static_cast<long>(lines.size());

  // column count from the first data line
  long cols = 1;
  {
    const char* q = lines[0];
    while (q < end && *q != '\n') {
      if (*q == delim) cols++;
      q++;
    }
  }

  float* data = static_cast<float*>(std::malloc(sizeof(float) * rows * cols));
  if (!data) {
    std::free(buf);
    return -2;
  }

  int nt = n_threads > 0 ? n_threads : 1;
  if (nt > rows) nt = static_cast<int>(rows);
  std::vector<std::thread> workers;
  std::vector<long> bad(nt, -1);
  long chunk = (rows + nt - 1) / nt;
  for (int t = 0; t < nt; t++) {
    long r0 = t * chunk;
    long r1 = std::min(rows, r0 + chunk);
    if (r0 >= r1) break;
    workers.emplace_back([&, r0, r1, t]() {
      for (long r = r0; r < r1; r++) {
        const char* lp = lines[r];
        const char* le = static_cast<const char*>(
            std::memchr(lp, '\n', end - lp));
        if (!le) le = end;
        long got = parse_line(lp, le, delim, data + r * cols, cols);
        if (got != cols && bad[t] < 0) bad[t] = r;
      }
    });
  }
  for (auto& w : workers) w.join();
  std::free(buf);
  for (int t = 0; t < nt; t++) {
    if (bad[t] >= 0) {
      std::free(data);
      return -5;                           // ragged row
    }
  }
  *out_data = data;
  *out_rows = rows;
  *out_cols = cols;
  return 0;
}

// ---------------------------------------------------------------------------
// IDX (MNIST-family) decoding
// ---------------------------------------------------------------------------

// Decode an IDX file of unsigned bytes (magic 0x0000 08 <ndim>).
// dims_out receives up to 4 dims; returns 0 on success.
int dl4jtpu_idx_read_u8(const char* path, uint8_t** out_data, int* out_ndim,
                        long dims_out[4]) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  uint8_t hdr[4];
  if (std::fread(hdr, 1, 4, f) != 4 || hdr[0] != 0 || hdr[1] != 0 ||
      hdr[2] != 0x08) {
    std::fclose(f);
    return -6;                             // not a u8 IDX file
  }
  int ndim = hdr[3];
  if (ndim < 1 || ndim > 4) {
    std::fclose(f);
    return -6;
  }
  long total = 1;
  const long kMaxTotal = 1L << 38;       // 256 GB sanity cap
  for (int i = 0; i < ndim; i++) {
    uint8_t d[4];
    if (std::fread(d, 1, 4, f) != 4) {
      std::fclose(f);
      return -3;
    }
    dims_out[i] = (static_cast<long>(d[0]) << 24) | (d[1] << 16) |
                  (d[2] << 8) | d[3];
    // overflow/corruption guard: file-supplied dims must stay sane
    if (dims_out[i] <= 0 || dims_out[i] > kMaxTotal / total) {
      std::fclose(f);
      return -6;
    }
    total *= dims_out[i];
  }
  uint8_t* data = static_cast<uint8_t*>(std::malloc(total));
  if (!data) {
    std::fclose(f);
    return -2;
  }
  if (std::fread(data, 1, total, f) != static_cast<size_t>(total)) {
    std::free(data);
    std::fclose(f);
    return -3;
  }
  std::fclose(f);
  *out_data = data;
  *out_ndim = ndim;
  return 0;
}

// ---------------------------------------------------------------------------
// uint8 -> float32 scale/shift (image normalization hot path)
// ---------------------------------------------------------------------------

void dl4jtpu_u8_to_f32_scaled(const uint8_t* src, float* dst, long n,
                              float scale, float shift, int n_threads) {
  int nt = n_threads > 0 ? n_threads : 1;
  long chunk = (n + nt - 1) / nt;
  std::vector<std::thread> workers;
  for (int t = 0; t < nt; t++) {
    long i0 = t * chunk;
    long i1 = std::min(n, i0 + chunk);
    if (i0 >= i1) break;
    workers.emplace_back([src, dst, i0, i1, scale, shift]() {
      for (long i = i0; i < i1; i++) {
        dst[i] = static_cast<float>(src[i]) * scale + shift;
      }
    });
  }
  for (auto& w : workers) w.join();
}

// library identity / version for the ctypes loader
const char* dl4jtpu_io_version() { return "dl4jtpu_io 1.2"; }

}  // extern "C"

// ---------------------------------------------------------------------------
// JPEG batch decode + resize (the ImageRecordReader hot path — the
// reference decodes through JavaCV/OpenCV natively; here libjpeg with its
// DCT-domain prescale + a bilinear resize to the target shape, threaded
// across files).  Compiled in when the system libjpeg headers exist
// (-DDL4JTPU_WITH_JPEG, see Makefile); dl4jtpu_has_jpeg() tells the
// Python side which path it got.
// ---------------------------------------------------------------------------

#ifdef DL4JTPU_WITH_JPEG
#include <csetjmp>
extern "C" {
#include <jpeglib.h>
}

namespace {

struct JpegErrCtx {
  jpeg_error_mgr mgr;
  std::jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErrCtx* ctx = reinterpret_cast<JpegErrCtx*>(cinfo->err);
  std::longjmp(ctx->jb, 1);
}

// setjmp-guarded phases keep only POD locals live across a potential
// longjmp (libjpeg error_exit): C++ objects (the pixel vector) live in
// the caller and are only touched through stable pointers — no skipped
// destructors, no indeterminate objects.

// phase 1: header + output geometry (with DCT-domain prescale chosen so
// most of the downscale happens for free inside the IDCT)
int jpeg_phase_header(jpeg_decompress_struct* cinfo, JpegErrCtx* err,
                      FILE* f, int H, int W, int C) {
  if (setjmp(err->jb)) return 1;
  jpeg_create_decompress(cinfo);
  jpeg_stdio_src(cinfo, f);
  jpeg_read_header(cinfo, TRUE);
  cinfo->out_color_space = (C == 1) ? JCS_GRAYSCALE : JCS_RGB;
  cinfo->scale_num = 1;
  cinfo->scale_denom = 1;
  while (cinfo->scale_denom < 8 &&
         (cinfo->image_width / (cinfo->scale_denom * 2)) >= (unsigned)W &&
         (cinfo->image_height / (cinfo->scale_denom * 2)) >= (unsigned)H) {
    cinfo->scale_denom *= 2;
  }
  jpeg_start_decompress(cinfo);
  return 0;
}

// phase 2: scanlines into a caller-owned buffer
int jpeg_phase_scan(jpeg_decompress_struct* cinfo, JpegErrCtx* err,
                    uint8_t* buf, size_t row_stride) {
  if (setjmp(err->jb)) return 1;
  while (cinfo->output_scanline < cinfo->output_height) {
    JSAMPROW row = buf + static_cast<size_t>(cinfo->output_scanline) * row_stride;
    jpeg_read_scanlines(cinfo, &row, 1);
  }
  jpeg_finish_decompress(cinfo);
  return 0;
}

// output-type policy for the bilinear store: float keeps the exact
// interpolated value; uint8 clamp-rounds (wire format for the uint8 ETL
// path — 4x fewer host->device bytes than f32, cast on device)
inline void store_px(float v, float* o) { *o = v; }
inline void store_px(float v, uint8_t* o) {
  int r = static_cast<int>(v + 0.5f);
  *o = static_cast<uint8_t>(r < 0 ? 0 : (r > 255 ? 255 : r));
}

// decode one file into out[H*W*C] (0..255), bilinear-resized.
template <typename T>
int decode_one_jpeg(const char* path, int H, int W, int C, T* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return 1;
  jpeg_decompress_struct cinfo;
  JpegErrCtx err;
  cinfo.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = jpeg_err_exit;
  std::vector<uint8_t> img;   // lives OUTSIDE every setjmp frame
  if (jpeg_phase_header(&cinfo, &err, f, H, W, C) != 0) {
    jpeg_destroy_decompress(&cinfo);
    std::fclose(f);
    return 2;
  }
  const int sw = cinfo.output_width, sh = cinfo.output_height;
  const int sc = cinfo.output_components;   // 1 or 3
  img.resize(static_cast<size_t>(sw) * sh * sc);
  if (jpeg_phase_scan(&cinfo, &err, img.data(),
                      static_cast<size_t>(sw) * sc) != 0) {
    jpeg_destroy_decompress(&cinfo);
    std::fclose(f);
    return 2;
  }
  jpeg_destroy_decompress(&cinfo);
  std::fclose(f);

  // bilinear resize (sh, sw, sc) u8 -> (H, W, C) f32; channel count match
  // guaranteed by out_color_space above (sc == C)
  const float ys = sh > 1 ? (float)(sh - 1) / (H > 1 ? H - 1 : 1) : 0.f;
  const float xs = sw > 1 ? (float)(sw - 1) / (W > 1 ? W - 1 : 1) : 0.f;
  for (int y = 0; y < H; y++) {
    float fy = y * ys;
    int y0 = (int)fy;
    int y1 = y0 + 1 < sh ? y0 + 1 : y0;
    float wy = fy - y0;
    for (int x = 0; x < W; x++) {
      float fx = x * xs;
      int x0 = (int)fx;
      int x1 = x0 + 1 < sw ? x0 + 1 : x0;
      float wx = fx - x0;
      const uint8_t* p00 = &img[(static_cast<size_t>(y0) * sw + x0) * sc];
      const uint8_t* p01 = &img[(static_cast<size_t>(y0) * sw + x1) * sc];
      const uint8_t* p10 = &img[(static_cast<size_t>(y1) * sw + x0) * sc];
      const uint8_t* p11 = &img[(static_cast<size_t>(y1) * sw + x1) * sc];
      T* o = &out[(static_cast<size_t>(y) * W + x) * C];
      for (int c = 0; c < C; c++) {
        float top = p00[c] + (p01[c] - p00[c]) * wx;
        float bot = p10[c] + (p11[c] - p10[c]) * wx;
        store_px(top + (bot - top) * wy, &o[c]);
      }
    }
  }
  return 0;
}

template <typename T>
int jpeg_batch_t(const char** paths, long n, int height, int width,
                 int channels, T* out, int n_threads) {
  int nt = n_threads > 0 ? n_threads : 1;
  if (nt > n) nt = (int)(n > 0 ? n : 1);
  const size_t stride = static_cast<size_t>(height) * width * channels;
  std::vector<int> fails(nt, 0);
  std::vector<std::thread> workers;
  for (int t = 0; t < nt; t++) {
    workers.emplace_back([&, t]() {
      for (long i = t; i < n; i += nt) {
        T* dst = out + stride * i;
        if (decode_one_jpeg(paths[i], height, width, channels, dst) != 0) {
          std::memset(dst, 0, stride * sizeof(T));
          fails[t]++;
        }
      }
    });
  }
  for (auto& w : workers) w.join();
  int total = 0;
  for (int v : fails) total += v;
  return total;
}

}  // namespace

extern "C" {

int dl4jtpu_has_jpeg() { return 1; }

// Decode n JPEG files into out[n*H*W*C] float32 (0..255), resized to
// (H, W, C), n_threads-way parallel over files.  Returns the number of
// files that FAILED to decode (their slots are zero-filled) — callers can
// treat nonzero as a warning or an error as they prefer.
int dl4jtpu_jpeg_batch(const char** paths, long n, int height, int width,
                       int channels, float* out, int n_threads) {
  return jpeg_batch_t(paths, n, height, width, channels, out, n_threads);
}

// uint8 wire-format variant: same decode+resize, clamp-rounded bytes —
// the batch ships host->device at 1/4 the f32 size and casts on device.
int dl4jtpu_jpeg_batch_u8(const char** paths, long n, int height, int width,
                          int channels, uint8_t* out, int n_threads) {
  return jpeg_batch_t(paths, n, height, width, channels, out, n_threads);
}

}  // extern "C"

#else  // !DL4JTPU_WITH_JPEG

extern "C" {
int dl4jtpu_has_jpeg() { return 0; }
int dl4jtpu_jpeg_batch_u8(const char**, long, int, int, int, uint8_t*, int) {
  return -1;
}
int dl4jtpu_jpeg_batch(const char**, long, int, int, int, float*, int) {
  return -1;
}
}  // extern "C"

#endif  // DL4JTPU_WITH_JPEG
